"""Chaos suite: seeded fault schedules against the live control plane.

Every recovery mechanism the runtime claims (task retries on worker
death, spillback + lineage after raylet/node death, crc-verified pulls,
graceful preemption drain with gang restart from the last committed
checkpoint) is exercised here by the chaos engine
(``_private/chaos.py``) instead of hand-rolled per-test kills. Fixed
seeds/schedules make every scenario replayable: the same ``RTPU_CHAOS``
against the same workload fires the same faults at the same points
(asserted by comparing chaos logs across two runs).

Reference analogue: the reference's NodeKillerActor / test_chaos.py
release suites (python/ray/_private/test_utils.py) — here the faults
are engine-driven and deterministic rather than timer-randomized.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import chaos
from ray_tpu._private.cluster_utils import Cluster


@pytest.fixture(autouse=True)
def _chaos_env_hygiene():
    """No chaos config may leak between tests (the env rides every
    process spawn)."""
    yield
    os.environ.pop("RTPU_CHAOS", None)
    os.environ.pop("RTPU_CHAOS_LOG", None)
    chaos.clear()


def _set_chaos(cfg, log_path=None):
    os.environ["RTPU_CHAOS"] = json.dumps(cfg)
    if log_path is not None:
        os.environ["RTPU_CHAOS_LOG"] = str(log_path)


def _driver():
    from ray_tpu._private import worker as wmod
    return wmod._global_worker


# ------------------------------------------------------------ engine unit


def test_engine_schedule_fires_deterministically():
    sched = [{"site": "worker.execute", "op": "mark", "at": 3},
             {"site": "worker.execute", "op": "mark2", "at": 2,
              "method": "f", "every": 2, "max_fires": 2}]
    e = chaos.ChaosEngine(seed=7, schedule=sched)
    hits = [e.hit("worker.execute", "g") for _ in range(5)]
    assert [h["op"] if h else None for h in hits] == \
        [None, None, "mark", None, None]
    # the method-filtered entry counts only matching hits
    hits_f = [e.hit("worker.execute", "f") for _ in range(7)]
    assert [h["op"] if h else None for h in hits_f] == \
        [None, "mark2", None, "mark2", None, None, None]


def test_engine_probabilistic_replay_same_seed():
    def run(seed):
        e = chaos.ChaosEngine(seed=seed, probs={"protocol.send.delay": 0.25})
        return [bool(e.hit("protocol.send", "m")) for _ in range(200)]

    a, b = run(11), run(11)
    assert a == b and any(a) and not all(a)
    assert run(12) != a  # a different seed is a different schedule


def test_engine_per_site_streams_independent():
    """Draw order on one site never perturbs another site's stream."""
    e1 = chaos.ChaosEngine(seed=3, probs={"a.x": 0.5, "b.x": 0.5})
    s_b1 = [bool(e1.hit("b")) for _ in range(50)]
    e2 = chaos.ChaosEngine(seed=3, probs={"a.x": 0.5, "b.x": 0.5})
    for _ in range(33):  # interleave site-a hits before touching b
        e2.hit("a")
    s_b2 = [bool(e2.hit("b")) for _ in range(50)]
    assert s_b1 == s_b2


def test_env_parse_forms():
    assert chaos.parse_env("42") == {"seed": 42}
    cfg = chaos.parse_env('{"seed": 1, "p": {"x.y": 0.5}}')
    assert cfg["seed"] == 1 and cfg["p"] == {"x.y": 0.5}
    os.environ["RTPU_CHAOS"] = "{not json"
    assert chaos.init_from_env("driver") is None  # malformed != fatal


# ----------------------------------------------- schedule 1: worker kill


def _run_worker_kill_workload(tmp_path, tag):
    """4 sequential tasks; the worker SIGKILLs itself at its 3rd
    execution; retries recover. Returns the run's chaos log."""
    log = tmp_path / f"chaos_{tag}.jsonl"
    _set_chaos({"seed": 1, "schedule": [
        {"site": "worker.execute", "op": "kill", "at": 3,
         "proc": "worker"}]}, log)
    ray_tpu.init(num_cpus=1, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_retries=3)
        def f(x):
            return x * 2

        out = [ray_tpu.get(f.remote(i), timeout=90) for i in range(4)]
        assert out == [0, 2, 4, 6]
    finally:
        ray_tpu.shutdown()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not chaos.read_log(str(log)):
        time.sleep(0.2)  # the killed worker's log write races its death
    return [(r["site"], r["op"], r["n"]) for r in chaos.read_log(str(log))]


def test_worker_kill_schedule_recovers_and_replays(tmp_path):
    """Schedule 1 (process layer): worker SIGKILL at a chosen task
    count; the owner's retry machinery recovers every result — and the
    same seed+schedule replays the same fault sequence."""
    run1 = _run_worker_kill_workload(tmp_path, "a")
    assert ("worker.execute", "kill", 3) in run1, run1
    run2 = _run_worker_kill_workload(tmp_path, "b")
    assert run1 == run2  # deterministic replay


def test_rpc_request_kill_recovers(tmp_path):
    """`rpc.request` site: a worker SIGKILLs itself before its N-th
    *served request* handler runs (any method — the site sits in both
    wire implementations' serve paths); the owner's retry machinery
    still recovers every result."""
    log = tmp_path / "chaos_rpc.jsonl"
    _set_chaos({"seed": 5, "schedule": [
        {"site": "rpc.request", "op": "kill", "at": 4,
         "proc": "worker"}]}, log)
    ray_tpu.init(num_cpus=1, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_retries=3)
        def f(x):
            return x + 10

        out = [ray_tpu.get(f.remote(i), timeout=90) for i in range(4)]
        assert out == [10, 11, 12, 13]
    finally:
        ray_tpu.shutdown()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not chaos.read_log(str(log)):
        time.sleep(0.2)
    fired = [(r["site"], r["op"]) for r in chaos.read_log(str(log))]
    assert ("rpc.request", "kill") in fired, fired


# ----------------------------------------------- schedule 2: raylet kill


def test_raylet_kill_recovery(tmp_path):
    """Schedule 2 (process layer): SIGKILL a non-head raylet at its 2nd
    dispatched task; the stuck demand is rescheduled once replacement
    capacity registers."""
    _set_chaos({"seed": 2, "schedule": [
        {"site": "raylet.dispatch", "op": "kill", "at": 2,
         "proc": "raylet", "head": False}]}, tmp_path / "chaos.jsonl")
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2, resources={"doomed": 1})
        cluster.connect()
        cluster.wait_for_nodes()

        @ray_tpu.remote(max_retries=3)
        def probe(x):
            return x + 1

        # dispatch 1 on the doomed raylet completes; dispatch 2 kills it
        assert ray_tpu.get(
            probe.options(resources={"doomed": 0.1}).remote(1),
            timeout=60) == 2
        victim = probe.options(resources={"doomed": 0.1}).remote(10)
        time.sleep(1.0)  # let the kill land
        # replacement capacity with the same custom resource arrives —
        # exactly the autoscaler/preemption-respawn pattern
        os.environ.pop("RTPU_CHAOS", None)  # replacement is chaos-free
        cluster.add_node(num_cpus=2, resources={"doomed": 1})
        # wait_for_nodes counts the dead raylet too — wait for a LIVE
        # node carrying the custom resource instead
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(n["alive"] and n["resources"].get("doomed")
                   for n in ray_tpu.nodes()):
                break
            time.sleep(0.2)
        assert ray_tpu.get(victim, timeout=120) == 11
    finally:
        cluster.shutdown()


# ------------------------------------- schedule 3: frame drop/delay/dup


def test_frame_faults_drop_delay_dup(tmp_path):
    """Schedule 3 (protocol layer): drop liveness beats, delay a result
    frame, duplicate a task_done — the cluster absorbs all three: no
    false node death, every result lands, no double resource release."""
    log = tmp_path / "chaos.jsonl"
    _set_chaos({"seed": 3, "schedule": [
        {"site": "protocol.send", "method": "node_liveness", "op": "drop",
         "at": 1, "every": 1, "max_fires": 2, "proc": "raylet"},
        {"site": "protocol.send", "method": "task_result", "op": "delay",
         "delay_s": 0.3, "at": 1, "proc": "worker"},
        {"site": "protocol.send", "method": "task_done", "op": "dup",
         "at": 2, "proc": "worker"},
    ]}, log)
    ray_tpu.init(num_cpus=2, resources={"pin": 4},
                 ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        # the custom resource keeps these off the lease fast lane, whose
        # replies carry results inline — this schedule targets the
        # raylet-routed task_result/task_done frames
        @ray_tpu.remote(resources={"pin": 0.1})
        def f(x):
            return x * 3

        assert [ray_tpu.get(f.remote(i), timeout=60) for i in range(4)] \
            == [0, 3, 6, 9]
        w = _driver()
        # duplicated task_done must not double-release: once quiesced,
        # available == total exactly
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            info = w.call_sync(w.raylet, "get_info", {})
            if info["available"].get("CPU") == \
                    info["resources"].get("CPU"):
                break
            time.sleep(0.2)
        assert info["available"].get("CPU") == info["resources"].get("CPU")
        # dropped heartbeats did not read as node death
        assert all(n["alive"] for n in ray_tpu.nodes())
        ops = {(r["site"], r["op"]) for r in chaos.read_log(str(log))}
        assert ("protocol.send", "delay") in ops
        assert ("protocol.send", "dup") in ops
    finally:
        ray_tpu.shutdown()


def test_connection_reset_recovers(tmp_path):
    """Schedule 3b (protocol layer): reset the worker→raylet link on the
    first task_done — the raylet sees a disconnect (worker death), the
    pool respawns, later tasks complete."""
    log = tmp_path / "chaos.jsonl"
    _set_chaos({"seed": 4, "schedule": [
        {"site": "protocol.send", "method": "task_done", "op": "reset",
         "at": 1, "proc": "worker"}]}, log)
    ray_tpu.init(num_cpus=1, resources={"pin": 4},
                 ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        # off the lease lane (leased tasks never send task_done)
        @ray_tpu.remote(max_retries=3, resources={"pin": 0.1})
        def f(x):
            return x + 7

        assert [ray_tpu.get(f.remote(i), timeout=90) for i in range(3)] \
            == [7, 8, 9]
        assert any(r["op"] == "reset"
                   for r in chaos.read_log(str(log)))
    finally:
        ray_tpu.shutdown()


# --------------------------- schedule 3c: frame faults, NATIVE pump


def test_frame_faults_native_pump(tmp_path, monkeypatch):
    """Schedule 3c: the PR-15 native frame pump exposes the same
    protocol.send/protocol.recv chaos sites at its frame boundary
    (docs/WIRE_PROTOCOL.md "Implementations"), so the frame-fault suite
    runs against the direct-execution lane too: delay + duplicate a
    leased_task request, then sever the direct connection mid-stream —
    every task still completes (dup is absorbed by reply-seq dedup,
    reset fails over to the batched raylet path), and the direct lane
    demonstrably carried traffic."""
    from ray_tpu._private import rpccore
    if rpccore._lib() is None:
        pytest.skip("native rpc library unavailable on this host")
    monkeypatch.setenv("RTPU_NATIVE_RPC", "1")
    log = tmp_path / "chaos.jsonl"
    _set_chaos({"seed": 6, "schedule": [
        {"site": "protocol.recv", "method": "leased_task", "op": "delay",
         "delay_s": 0.2, "at": 2, "proc": "worker"},
        {"site": "protocol.recv", "method": "leased_task", "op": "dup",
         "at": 4, "proc": "worker"},
        {"site": "protocol.recv", "method": "leased_task", "op": "reset",
         "at": 6, "proc": "worker"},
    ]}, log)
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_retries=3)
        def f(x):
            return x * 5

        # CPU-only no-dep tasks ride the direct lane; the schedule fires
        # inside the native pump's recv path on the worker side
        assert [ray_tpu.get(f.remote(i), timeout=90) for i in range(10)] \
            == [5 * i for i in range(10)]
        from ray_tpu._private import worker as wmod
        dc = wmod._global_worker._direct_client
        assert dc is not None and dc.submitted > 0, \
            "direct lane saw no traffic — faults not exercised there"
        ops = {r["op"] for r in chaos.read_log(str(log))
               if r["site"] == "protocol.recv"}
        assert {"delay", "dup", "reset"} <= ops, ops
    finally:
        ray_tpu.shutdown()


# ------------------------------------------- schedule 4: object plane


def test_object_evict_lineage_reconstruction(tmp_path):
    """Schedule 4 (object plane): the primary copy is evicted right
    before the first pull — the owner reconstructs via lineage resubmit
    and the value comes back intact."""
    log = tmp_path / "chaos.jsonl"
    _set_chaos({"seed": 5, "schedule": [
        {"site": "object.pull", "op": "evict", "at": 1,
         "proc": "raylet", "head": False}]}, log)
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2, resources={"nodeB": 1})
        cluster.connect()
        cluster.wait_for_nodes()

        @ray_tpu.remote(max_retries=3, resources={"nodeB": 0.1})
        def produce():
            return np.full(512 * 1024, 9, dtype=np.uint8)  # 512 KB

        v = ray_tpu.get(produce.remote(), timeout=120)
        assert v.nbytes == 512 * 1024 and int(v[0]) == 9
        assert any(r["op"] == "evict" for r in chaos.read_log(str(log)))
    finally:
        cluster.shutdown()


def test_object_corrupt_crc_detected_and_retried(tmp_path):
    """Schedule 4b (object plane): the first pull chunk is corrupted in
    flight — the receiver's crc check rejects the replica and the retry
    pass fetches a clean copy (the corrupt bytes are never sealed)."""
    log = tmp_path / "chaos.jsonl"
    _set_chaos({"seed": 6, "schedule": [
        {"site": "object.pull", "op": "corrupt", "at": 1,
         "proc": "raylet", "head": False}]}, log)
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2, resources={"nodeB": 1})
        cluster.connect()
        cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"nodeB": 0.1})
        def produce():
            return np.arange(256 * 1024, dtype=np.int64)  # 2 MB

        v = ray_tpu.get(produce.remote(), timeout=120)
        assert int(v.sum()) == int(
            np.arange(256 * 1024, dtype=np.int64).sum())
        assert any(r["op"] == "corrupt" for r in chaos.read_log(str(log)))
    finally:
        cluster.shutdown()


# -------------------------------------- schedule 5: preemption drain e2e


def _events(w, label=None):
    evs = w.call_sync(w.gcs, "list_events", {"limit": 1000})
    if label is None:
        return evs
    return [e for e in evs if e.get("label") == label]


def test_preemption_drain_end_to_end(tmp_path):
    """Schedule 5: preemption notice → raylet drains (stops leases,
    marks draining in the GCS node table) → the trainer commits an
    out-of-band checkpoint through AsyncCheckpointer inside the grace
    window → the node dies → gang restart resumes from
    latest_committed() on the surviving node — with the whole
    fault→detect→recover timeline in the structured event stream."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.checkpoint import CheckpointManager
    from ray_tpu.train import DataParallelTrainer

    marker = str(tmp_path / "oob_step")
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    try:
        cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)
        cluster.connect()
        cluster.wait_for_nodes()
        w = _driver()

        def train_fn(config):
            from ray_tpu.air import session
            ckpter = session.get_async_checkpointer()
            start = 0
            if session.get_checkpoint() is not None:
                # sharded resume: reassemble onto the target pytree
                state = session.get_checkpoint_manager().restore_state(
                    {"i": np.asarray(0.0)})
                start = int(np.asarray(state["i"]).reshape(-1)[0]) + 1
            oob_done = False
            for i in range(start, 80):
                time.sleep(0.12)
                if session.preempted() and not oob_done:
                    # the preemption out-of-band commit: save NOW, not
                    # at the periodic cadence
                    oob_done = True
                    step = session.next_checkpoint_step()
                    pending = ckpter.save(step,
                                          {"i": np.asarray(float(i))})
                    with open(config["marker"], "w") as f:
                        f.write(str(step))
                    session.report({"i": i, "oob": 1},
                                   checkpoint=pending)
                elif i % 5 == 0:
                    pending = ckpter.save(session.next_checkpoint_step(),
                                          {"i": np.asarray(float(i))})
                    session.report({"i": i}, checkpoint=pending)
                else:
                    session.report({"i": i})
            ckpter.finalize()

        preempted_node = {}

        def deliver_preemption():
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                try:
                    actors = w.call_sync(w.gcs, "list_actors", {})
                except Exception:
                    time.sleep(0.3)
                    continue
                alive = [a for a in actors
                         if a.get("state") == "ALIVE"
                         and "TrainWorker" in (a.get("class_name") or "")]
                if alive:
                    time.sleep(1.5)  # let a few steps + a commit land
                    preempted_node["id"] = alive[0]["node_id"]
                    w.call_sync(w.gcs, "preempt_node", {
                        "node_id": alive[0]["node_id"],
                        "grace_s": 3.0, "reason": "test spot notice"})
                    return
                time.sleep(0.3)

        killer = threading.Thread(target=deliver_preemption, daemon=True)
        killer.start()
        trainer = DataParallelTrainer(
            train_fn, train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="preempt_drain", storage_path=str(tmp_path),
                stop={"i": 50},
                failure_config=FailureConfig(max_failures=2)))
        result = trainer.fit()
        killer.join(timeout=10)
        assert result.error is None, result.error
        assert preempted_node, "preemption was never delivered"

        # the out-of-band checkpoint committed inside the grace window
        root = os.path.join(str(tmp_path), "preempt_drain", "checkpoints")
        mgr = CheckpointManager(root)
        assert os.path.exists(marker), "train_fn never saw preempted()"
        oob_step = int(open(marker).read())
        assert mgr.is_committed(oob_step), \
            f"out-of-band step {oob_step} not committed; " \
            f"committed={mgr.committed_steps()}"

        # the preempted node is dead (graceful node_drained, not
        # heartbeat timeout) and the gang resumed elsewhere
        nodes = {n["node_id"]: n for n in ray_tpu.nodes()}
        assert not nodes[preempted_node["id"]]["alive"]

        # fault → detect → recover timeline in one event stream
        notice = _events(w, "PREEMPTION_NOTICE")
        draining = _events(w, "NODE_DRAINING")
        restart = _events(w, "TRAIN_GANG_RESTART")
        resumed = _events(w, "TRAIN_RESUMED")
        assert notice and draining and restart and resumed
        recovery_s = resumed[-1]["timestamp"] - notice[0]["timestamp"]
        assert 0 < recovery_s < 120
        print(f"preemption recovery latency: {recovery_s:.2f}s")
    finally:
        cluster.shutdown()


# ------------------------------------------- workload breadth under chaos


def test_serve_burst_under_frame_delays(tmp_path):
    """Serve traffic burst with periodic actor-call frame delays: every
    request still answers (the data plane absorbs protocol jitter)."""
    _set_chaos({"seed": 8, "schedule": [
        {"site": "protocol.recv", "method": "actor_call", "op": "delay",
         "delay_s": 0.15, "at": 3, "every": 7, "max_fires": 4,
         "proc": "worker"}]})
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        from ray_tpu import serve

        @serve.deployment(num_replicas=1)
        def echo(x):
            return x * 2

        handle = serve.run(echo.bind(), http_port=None)
        out = ray_tpu.get([handle.remote(i) for i in range(30)],
                          timeout=120)
        assert out == [i * 2 for i in range(30)]
        serve.shutdown()
    finally:
        ray_tpu.shutdown()


def test_data_pipeline_under_worker_kills(tmp_path):
    """Data pipeline with worker SIGKILLs mid-map: retries keep the
    results exactly-once-per-row correct."""
    _set_chaos({"seed": 9, "schedule": [
        {"site": "worker.execute", "op": "kill", "at": 2,
         "proc": "worker"}]})
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        from ray_tpu import data
        ds = data.range(6).map(lambda x: x * 10)
        assert sorted(ds.take_all()) == [0, 10, 20, 30, 40, 50]
    finally:
        ray_tpu.shutdown()


# ----------------------------------------------------------- slow soak


@pytest.mark.slow
@pytest.mark.parametrize("seed", [101, 202])
def test_randomized_soak(tmp_path, seed):
    """Randomized (but seeded, hence replayable) soak: low-probability
    frame delays/drops on fire-and-forget channels across the whole
    cluster while a mixed task/data workload runs to completion."""
    _set_chaos({"seed": seed, "delay_s": 0.03, "p": {
        "protocol.send.delay": 0.02,
        "protocol.recv.delay": 0.02,
        "protocol.send.publish.drop": 0.2,
    }})
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_retries=3)
        def sq(x):
            return x * x

        assert ray_tpu.get([sq.remote(i) for i in range(40)],
                           timeout=180) == [i * i for i in range(40)]
        from ray_tpu import data
        ds = data.range(12).map(lambda x: x + 1)
        assert sorted(ds.take_all()) == list(range(1, 13))
    finally:
        ray_tpu.shutdown()
