"""rtpulint: the static-analysis gate and its checker fixture matrix.

Three layers:

1. **fixture matrix** — every checker has at least one true-positive
   and one false-positive fixture, plus pragma suppression;
2. **registry round-trips** — the chaos-site and env-var registries
   are checked against the *live tree* in both directions (every use
   declared, every declaration used/exercised), and the generated docs
   must be byte-fresh;
3. **the gate** — `ray_tpu/` must analyze clean modulo the reviewed
   baseline (no unsuppressed findings, no stale baseline entries).
   This is the tier-1 enforcement point: a PR that introduces a
   blocking call in an async def (etc.) fails here.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.analysis import analyze_paths, analyze_source
from ray_tpu.analysis import baseline as bl
from ray_tpu.analysis.core import analyze_file, registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "ray_tpu")

ALL_CODES = {"RTPU001", "RTPU002", "RTPU003", "RTPU004", "RTPU005",
             "RTPU006", "RTPU007"}


def check(src, select=None, config=None, relpath=None, pragmas=True):
    return analyze_source(textwrap.dedent(src), relpath=relpath,
                          config=config, select=select,
                          respect_pragmas=pragmas)


def codes(findings):
    return sorted(f.code for f in findings)


def test_registry_has_all_checkers():
    assert set(registry()) == ALL_CODES


# --------------------------------------------------------------- RTPU001


def test_blocking_in_async_def_flagged():
    fs = check("""
        import time
        async def f():
            time.sleep(1)
    """, select=["RTPU001"])
    assert codes(fs) == ["RTPU001"]
    assert "time.sleep" in fs[0].message


def test_blocking_in_sync_def_ok():
    assert check("""
        import time
        def f():
            time.sleep(1)
    """, select=["RTPU001"]) == []


def test_await_sleep_ok():
    assert check("""
        import asyncio
        async def f():
            await asyncio.sleep(1)
    """, select=["RTPU001"]) == []


def test_nested_sync_def_inside_async_ok():
    # the nested def runs wherever it's called (thread pool, executor),
    # not on the event loop of the enclosing coroutine
    assert check("""
        import time
        async def f(loop):
            def worker():
                time.sleep(1)
            await loop.run_in_executor(None, worker)
    """, select=["RTPU001"]) == []


def test_blocking_pragma_suppression():
    src = """
        import time
        async def f():
            time.sleep(0)  # rtpulint: ignore[RTPU001]
    """
    assert check(src, select=["RTPU001"]) == []
    assert codes(check(src, select=["RTPU001"], pragmas=False)) == \
        ["RTPU001"]


def test_config_extends_blocking_calls():
    fs = check("""
        async def f():
            heavy_io()
    """, select=["RTPU001"], config={"blocking_calls": ["heavy_io"]})
    assert codes(fs) == ["RTPU001"]


# --------------------------------------------------------------- RTPU002


def test_lock_across_await_flagged():
    fs = check("""
        async def f(self):
            with self._lock:
                await self.flush()
    """, select=["RTPU002"])
    assert codes(fs) == ["RTPU002"]


def test_lock_without_await_ok():
    assert check("""
        async def f(self):
            with self._lock:
                self.n += 1
    """, select=["RTPU002"]) == []


def test_async_lock_across_await_ok():
    assert check("""
        async def f(self):
            async with self._lock:
                await self.flush()
    """, select=["RTPU002"]) == []


# --------------------------------------------------------------- RTPU003


def test_daemon_thread_without_stop_flagged():
    fs = check("""
        import threading
        class Flusher:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
    """, select=["RTPU003"])
    assert codes(fs) == ["RTPU003"]
    assert "daemon thread" in fs[0].message


def test_daemon_thread_with_stop_ok():
    assert check("""
        import threading
        class Flusher:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
            def stop(self):
                self._stop = True
                self._t.join()
    """, select=["RTPU003"]) == []


def test_incref_without_decref_flagged():
    fs = check("""
        class Pages:
            def grab(self, pool, pid):
                pool.incref(pid)
    """, select=["RTPU003"])
    assert codes(fs) == ["RTPU003"]
    assert "decref" in fs[0].message


def test_incref_decref_paired_ok():
    assert check("""
        class Pages:
            def grab(self, pool, pid):
                pool.incref(pid)
            def drop(self, pool, pid):
                pool.decref(pid)
    """, select=["RTPU003"]) == []


def test_error_path_decref_leak_flagged():
    fs = check("""
        def ship(pool, pid, conn):
            pool.incref(pid)
            conn.send(pid)
            pool.decref(pid)
    """, select=["RTPU003"])
    assert codes(fs) == ["RTPU003"]
    assert "straight-line" in fs[0].message


def test_error_path_decref_in_finally_ok():
    assert check("""
        def ship(pool, pid, conn):
            pool.incref(pid)
            try:
                conn.send(pid)
            finally:
                pool.decref(pid)
    """, select=["RTPU003"]) == []


# --------------------------------------------------------------- RTPU004

_SITES_CFG = {"chaos_sites": ["raylet.dispatch", "protocol.send"]}


def test_undeclared_chaos_site_flagged_with_near_miss():
    fs = check("""
        from ray_tpu._private import chaos
        def f():
            chaos.hit("raylet.dispach", None)
    """, select=["RTPU004"], config=_SITES_CFG)
    assert codes(fs) == ["RTPU004"]
    assert "raylet.dispatch" in fs[0].message  # did-you-mean hint


def test_declared_chaos_site_ok():
    assert check("""
        from ray_tpu._private import chaos
        def f():
            chaos.hit("raylet.dispatch", None)
    """, select=["RTPU004"], config=_SITES_CFG) == []


def test_chaos_site_module_constant_resolved():
    assert check("""
        from ray_tpu._private import chaos
        CHAOS_SITE = "protocol.send"
        def f():
            chaos.hit(CHAOS_SITE, None)
    """, select=["RTPU004"], config=_SITES_CFG) == []


def test_chaos_site_unresolvable_flagged():
    fs = check("""
        from ray_tpu._private import chaos
        def f(site):
            chaos.hit(site, None)
    """, select=["RTPU004"], config=_SITES_CFG)
    assert codes(fs) == ["RTPU004"]
    assert "statically" in fs[0].message


# --------------------------------------------------------------- RTPU005

_ENV_CFG = {"env_registry": ["RTPU_TRACE_SAMPLE", "RTPU_CHAOS"]}


def test_unregistered_env_read_flagged():
    fs = check("""
        import os
        v = os.environ.get("RTPU_BRAND_NEW_KNOB")
    """, select=["RTPU005"], config=_ENV_CFG)
    assert codes(fs) == ["RTPU005"]


def test_env_typo_near_miss_message():
    fs = check("""
        import os
        v = os.environ.get("RTPU_TRACE_SAMPEL")
    """, select=["RTPU005"], config=_ENV_CFG)
    assert codes(fs) == ["RTPU005"]
    assert "RTPU_TRACE_SAMPLE" in fs[0].message
    assert "typo" in fs[0].message


def test_registered_env_reads_ok_all_idioms():
    assert check("""
        import os
        a = os.environ.get("RTPU_CHAOS")
        b = os.getenv("RTPU_TRACE_SAMPLE")
        c = os.environ["RTPU_CHAOS"]
        d = "RTPU_CHAOS" in os.environ
        e = os.environ.setdefault("RTPU_TRACE_SAMPLE", "1.0")
    """, select=["RTPU005"], config=_ENV_CFG) == []


def test_non_rtpu_env_reads_ignored():
    assert check("""
        import os
        v = os.environ.get("HOME")
    """, select=["RTPU005"], config=_ENV_CFG) == []


# --------------------------------------------------------------- RTPU006

_FV_CFG = {"field_versions": {("dag_exec", "tc"): (1, 6),
                              ("worker_register", "direct_address"): (1, 7),
                              ("release_lease", "inflight"): (1, 2)}}


def test_unguarded_hard_read_flagged():
    fs = check("""
        def handle(payload):
            return payload["tc"]
    """, select=["RTPU006"], config=_FV_CFG)
    assert codes(fs) == ["RTPU006"]
    assert "1.6" in fs[0].message


def test_get_read_is_absence_tolerant():
    # the dag/channel.py receive-side idiom: .get() + truthiness
    assert check("""
        def handle(payload):
            tc = payload.get("tc")
            if tc:
                attach(tc)
    """, select=["RTPU006"], config=_FV_CFG) == []


def test_tuple_compare_guard_recognized():
    # the schema-1.2 lease idiom: explicit negotiated-version compare
    assert check("""
        def handle(self, payload, conn):
            ver = conn.meta.get("peer_protocol_version") or (1, 0)
            if tuple(ver[:2]) >= (1, 2):
                return payload["inflight"]
            return 0
    """, select=["RTPU006"], config=_FV_CFG) == []


def test_negotiated_flag_guard_recognized():
    # the compiled_dag._negotiate 1.6 idiom: a feature flag computed
    # from the min peer version gates the hard read
    assert check("""
        def recv(self, payload):
            if self._trace_peers:
                span(payload["tc"])
    """, select=["RTPU006"], config=_FV_CFG) == []


def test_min_peer_guard_recognized():
    # the 1.7 direct-lane idiom
    assert check("""
        def register(self, payload, min_peer):
            if min_peer >= (1, 7):
                return payload["direct_address"]
    """, select=["RTPU006"], config=_FV_CFG) == []


def test_field_write_not_flagged():
    # producing the field is fine — we only speak what WE negotiated
    assert check("""
        def build(payload, ctx):
            payload["tc"] = ctx
    """, select=["RTPU006"], config=_FV_CFG) == []


def test_ungated_field_read_ok():
    assert check("""
        def handle(payload):
            return payload["method"]
    """, select=["RTPU006"], config=_FV_CFG) == []


def test_live_tree_version_gate_idioms_pass():
    """dag/channel.py and _private/direct.py read 1.5/1.6/1.7 fields
    behind this codebase's real guard idioms — the checker must
    recognize all of them (zero findings, no pragmas needed)."""
    for rel in ("dag/channel.py", "_private/direct.py"):
        path = os.path.join(PKG, rel)
        fs = analyze_file(path, root=PKG, select=["RTPU006"])
        assert fs == [], f"{rel}: {[f.render() for f in fs]}"


# --------------------------------------------------------------- RTPU007


def test_inert_swallow_in_control_loop_flagged():
    fs = check("""
        def tick(self):
            while True:
                try:
                    self.step()
                except Exception:
                    pass
    """, select=["RTPU007"], relpath="serve/controller.py")
    assert codes(fs) == ["RTPU007"]


def test_swallow_that_logs_ok():
    assert check("""
        def tick(self):
            while True:
                try:
                    self.step()
                except Exception:
                    logger.exception("tick failed")
    """, select=["RTPU007"], relpath="serve/controller.py") == []


def test_swallow_that_records_ok():
    # stashing the error IS a keep-going policy, not silence
    assert check("""
        def tick(self):
            while True:
                try:
                    self.step()
                except Exception as e:
                    self._last_error = e
    """, select=["RTPU007"], relpath="serve/controller.py") == []


def test_swallow_outside_loop_ok():
    assert check("""
        def once(self):
            try:
                self.step()
            except Exception:
                pass
    """, select=["RTPU007"], relpath="serve/controller.py") == []


def test_swallow_outside_control_plane_ok():
    assert check("""
        def tick(self):
            while True:
                try:
                    self.step()
                except Exception:
                    pass
    """, select=["RTPU007"], relpath="util/helpers.py") == []


def test_swallow_pragma_on_except_line():
    assert check("""
        def tick(self):
            while True:
                try:
                    self.step()
                except Exception:  # rtpulint: ignore[RTPU007]
                    pass
    """, select=["RTPU007"], relpath="serve/controller.py") == []


# ------------------------------------------------------------- pragmas


def test_bare_pragma_suppresses_all_codes():
    assert check("""
        import time
        async def f():
            time.sleep(1)  # rtpulint: ignore
    """) == []


def test_own_line_pragma_covers_next_line():
    assert check("""
        import time
        async def f():
            # rtpulint: ignore[RTPU001]
            time.sleep(1)
    """, select=["RTPU001"]) == []


def test_pragma_wrong_code_does_not_suppress():
    fs = check("""
        import time
        async def f():
            time.sleep(1)  # rtpulint: ignore[RTPU002]
    """, select=["RTPU001"])
    assert codes(fs) == ["RTPU001"]


# ------------------------------------------------------------- baseline


def _one_finding(src="""
    import time
    async def f():
        time.sleep(1)
"""):
    fs = check(src, select=["RTPU001"], relpath="pkg/mod.py")
    assert len(fs) == 1
    return fs[0]


def test_baseline_round_trip(tmp_path):
    f = _one_finding()
    p = tmp_path / "bl"
    bl.save(str(p), [f])
    entries = bl.load(str(p))  # --write-baseline emits a TODO comment
    assert len(entries) == 1
    assert entries[0].code == "RTPU001"
    assert entries[0].fingerprint == f.fingerprint()
    un, based, stale = bl.apply([f], entries)
    assert un == [] and based == [f] and stale == []


def test_baseline_requires_justification(tmp_path):
    f = _one_finding()
    p = tmp_path / "bl"
    p.write_text(f"{f.code} {f.relpath} {f.scope} {f.fingerprint()}\n")
    with pytest.raises(ValueError, match="justification"):
        bl.load(str(p))


def test_baseline_rejects_malformed_line(tmp_path):
    p = tmp_path / "bl"
    p.write_text("what even is this\n")
    with pytest.raises(ValueError, match="malformed"):
        bl.load(str(p))


def test_baseline_stale_entry_surfaces(tmp_path):
    f = _one_finding()
    p = tmp_path / "bl"
    p.write_text(f"RTPU001 {f.relpath} {f.scope} {'0' * 12}"
                 f"  # fixed long ago\n")
    un, based, stale = bl.apply([f], bl.load(str(p)))
    assert un == [f] and based == []
    assert len(stale) == 1  # must be deleted: baselines only shrink


def test_fingerprint_stable_across_line_moves():
    a = _one_finding()
    b = _one_finding("""


    import time
    async def f():
        time.sleep(1)
""")
    assert a.line != b.line
    assert a.fingerprint() == b.fingerprint()


def test_fingerprint_changes_with_code_change():
    a = _one_finding()
    b = check("""
        import subprocess
        async def f():
            subprocess.run(["x"])
    """, select=["RTPU001"], relpath="pkg/mod.py")[0]
    assert a.fingerprint() != b.fingerprint()


# ----------------------------------------------------- registry round-trips


def _hit_sites_in_tree():
    """Every chaos.hit site literal in ray_tpu/ (the checker's view)."""
    from ray_tpu.analysis.core import (call_name, const_str,
                                       iter_py_files, module_constants)
    sites = {}
    for fp in iter_py_files([PKG]):
        with open(fp, encoding="utf-8", errors="replace") as fh:
            try:
                tree = ast.parse(fh.read())
            except SyntaxError:
                continue
        if fp.replace(os.sep, "/").endswith("_private/chaos.py"):
            continue
        consts = module_constants(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = call_name(node)
            if name is None or not (name.rsplit(".", 1)[-1] == "hit"
                                    or name == "chaos_hit"):
                continue
            site = const_str(node.args[0])
            if site is None and isinstance(node.args[0], ast.Name):
                site = consts.get(node.args[0].id)
            if site:
                sites.setdefault(site, []).append(fp)
    return sites


def test_chaos_registry_round_trip():
    """Both directions against the live tree: every hit site declared
    (RTPU004's job), and every declared site actually hit somewhere —
    a registry row nothing fires is a fault path nothing exercises."""
    from ray_tpu._private.chaos import SITES
    used = _hit_sites_in_tree()
    assert set(used) <= set(SITES), \
        f"undeclared sites in tree: {set(used) - set(SITES)}"
    assert set(SITES) <= set(used), \
        f"declared but never hit: {set(SITES) - set(used)}"


def test_every_chaos_site_exercised_by_tests():
    from ray_tpu._private.chaos import SITES
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    corpus = ""
    for fn in os.listdir(tests_dir):
        if fn.endswith(".py"):
            with open(os.path.join(tests_dir, fn),
                      encoding="utf-8", errors="replace") as fh:
                corpus += fh.read()
    unexercised = [s for s in SITES if s not in corpus]
    assert unexercised == [], \
        f"chaos sites no test injects into: {unexercised}"


def test_env_registry_round_trip():
    """Every RTPU_* read in the tree is registered (the RTPU005 gate,
    asserted directly), and every *static* registry entry corresponds
    to a name the tree actually mentions — entries for removed knobs
    must be deleted, not accumulate."""
    from ray_tpu.analysis.config_registry import (CONFIG_VARS,
                                                  STATIC_VARS)
    from ray_tpu.analysis.docs_gen import scan_env_reads
    scan_paths = [PKG, os.path.dirname(os.path.abspath(__file__)),
                  os.path.join(REPO_ROOT, "bench.py")]
    reads = scan_env_reads(scan_paths, REPO_ROOT)
    unregistered = sorted(n for n in reads if n not in CONFIG_VARS)
    assert unregistered == [], \
        f"env reads missing from config_registry: {unregistered}"

    corpus = ""
    for fp in _all_py(scan_paths):
        with open(fp, encoding="utf-8", errors="replace") as fh:
            corpus += fh.read()
    dead = sorted(n for n in STATIC_VARS if n not in corpus)
    assert dead == [], f"registry entries nothing mentions: {dead}"


def _all_py(paths):
    from ray_tpu.analysis.core import iter_py_files
    return iter_py_files(paths)


def test_generated_docs_are_fresh():
    """docs/CONFIGURATION.md and the chaos table in
    docs/FAULT_TOLERANCE.md must match a regeneration byte-for-byte —
    run `python -m ray_tpu.analysis --gen-docs` after touching the
    registries."""
    from ray_tpu.analysis.docs_gen import generate_all
    stale = [os.path.relpath(p, REPO_ROOT)
             for p, (_c, changed) in
             generate_all(REPO_ROOT, write=False).items() if changed]
    assert stale == [], f"stale generated docs: {stale}"


# ------------------------------------------------------------- the gate


def test_ray_tpu_tree_lints_clean():
    """THE gate: zero unsuppressed findings over ray_tpu/, no stale
    baseline entries. New findings either get fixed, carry an inline
    `# rtpulint: ignore[...]` pragma with a reason, or (reviewed) join
    .rtpulint-baseline with a justification."""
    from ray_tpu.analysis.cli import DEFAULT_EXCLUDES
    findings = analyze_paths([PKG], root=PKG, exclude=DEFAULT_EXCLUDES)
    entries = bl.load(os.path.join(REPO_ROOT, bl.DEFAULT_BASENAME))
    assert len(entries) < 15, "baseline must stay small — fix, don't park"
    unsuppressed, _based, stale = bl.apply(findings, entries)
    assert unsuppressed == [], "\n".join(f.render() for f in unsuppressed)
    assert stale == [], \
        f"stale baseline entries (delete them): {[e.key() for e in stale]}"


def test_cli_json_smoke():
    """`ray-tpu lint --json` end to end in a subprocess (the scripts/cli
    delegation path), machine-readable output contract."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "from ray_tpu.scripts.cli import main; "
         "main(['lint', '--json', 'ray_tpu'])"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, "PYTHONPATH": REPO_ROOT}, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    assert doc["findings"] == []
    assert doc["stale_baseline"] == []
    assert set(doc["checkers"]) == ALL_CODES


def test_syntax_error_reported_as_rtpu000(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("def f(:\n")
    fs = analyze_file(str(p), root=str(tmp_path))
    assert codes(fs) == ["RTPU000"]
