"""Tests for the parallel layer (mesh/sharding/collectives) and ops
(flash attention kernel in interpret mode, ring attention on the virtual
8-device CPU mesh)."""

import numpy as np
import pytest


def test_mesh_spec_build(cpu_mesh8):
    from ray_tpu.parallel.mesh import MeshSpec
    import jax

    spec = MeshSpec(dp=2, tp=4)
    assert spec.num_devices == 8
    mesh = spec.build(jax.devices("cpu")[:8])
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


def test_mesh_spec_validation():
    from ray_tpu.parallel.mesh import MeshSpec
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"bogus": 2})
    spec = MeshSpec(tp=4)
    assert spec.with_auto_dp(8).dp == 2


def test_param_sharding_rules(cpu_mesh8):
    import jax.numpy as jnp
    from ray_tpu.parallel.mesh import MeshSpec, shard_params
    import jax

    mesh = MeshSpec(dp=2, tp=4).build(jax.devices("cpu")[:8])
    params = {
        "dense": {"kernel": jnp.ones((256, 512)), "bias": jnp.ones((512,))},
        "out_proj": {"kernel": jnp.ones((512, 256))},
    }
    sharded = shard_params(params, mesh, MeshSpec(dp=2, tp=4))
    # output dim of generic kernels shards over tp
    k_shard = sharded["dense"]["kernel"].sharding.spec
    assert "tp" in str(k_shard)


def test_data_parallel_psum(cpu_mesh8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(dp=8).build(jax.devices("cpu")[:8])
    x = jnp.arange(32.0).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))

    @jax.jit
    def mean_all(x):
        return x.mean()

    assert np.isclose(float(mean_all(xs)), float(x.mean()))


def test_collective_group_allreduce(cpu_mesh8):
    import jax
    import jax.numpy as jnp
    from ray_tpu.parallel import collectives

    g = collectives.init_collective_group(8, 0, group_name="t",
                                          devices=jax.devices("cpu")[:8])
    x = jnp.ones((8, 4))
    out = g.allreduce(x, op="sum")
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))
    collectives.destroy_collective_group("t")


def test_flash_attention_forward_matches_reference():
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops.attention import (attention_reference, flash_attention)

    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (2, 2, 128, 64)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = attention_reference(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, force_pallas=True,
                          interpret=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_causal_matches_reference():
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops.attention import (attention_reference, flash_attention)

    rng = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (1, 2, 128, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, force_pallas=True,
                          interpret=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_grads_match_reference():
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops.attention import (attention_reference, flash_attention)

    rng = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (1, 1, 64, 32)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, force_pallas=True,
                               interpret=True, block_q=32, block_k=32).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_attention_whole_vs_streaming_paths(monkeypatch):
    """The short-sequence whole-kv kernels and the streaming flash
    kernels must agree with each other and the reference — fwd and
    grads (RTPU_ATTN_EXACT=1 forces the streaming path)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops import attention as A

    rng = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (2, 2, 256, 64)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    assert A._use_whole_kv(256, 256, 64)

    def loss(fn):
        return lambda q, k, v: fn(q, k, v).sum()

    def flash(q, k, v):
        return A.flash_attention(q, k, v, causal=True, force_pallas=True,
                                 interpret=True, block_q=128, block_k=128)

    ref = A.attention_reference(q, k, v, causal=True)
    out_whole = flash(q, k, v)
    g_whole = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("RTPU_ATTN_EXACT", "1")
    assert not A._use_whole_kv(256, 256, 64)
    out_stream = flash(q, k, v)
    g_stream = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    monkeypatch.delenv("RTPU_ATTN_EXACT")

    np.testing.assert_allclose(np.asarray(out_whole), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(out_stream), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    for a, b in zip(g_whole, g_stream):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_attention_exact_kwarg_overrides_env(monkeypatch):
    """`exact=` picks the softmax numerics per call (ADVICE round 5:
    the env var was trace-time-only): exact=True forces the streaming
    kernels, exact=False allows the whole-kv fast path, None defers to
    RTPU_ATTN_EXACT — and both paths agree with the reference."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops import attention as A

    assert A._use_whole_kv(256, 256, 64)
    assert not A._use_whole_kv(256, 256, 64, True)
    assert A._use_whole_kv(256, 256, 64, False)
    # an explicit exact=False overrides even the env var
    monkeypatch.setenv("RTPU_ATTN_EXACT", "1")
    assert not A._use_whole_kv(256, 256, 64)  # env applies when None
    assert A._use_whole_kv(256, 256, 64, False)
    monkeypatch.delenv("RTPU_ATTN_EXACT")

    rng = jax.random.PRNGKey(11)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (1, 2, 256, 64)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = A.attention_reference(q, k, v, causal=True)
    for exact in (True, False):
        out = A.flash_attention(q, k, v, causal=True, force_pallas=True,
                                interpret=True, block_q=128, block_k=128,
                                exact=exact)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        g = jax.grad(lambda q, k, v: A.flash_attention(
            q, k, v, causal=True, force_pallas=True, interpret=True,
            block_q=128, block_k=128, exact=exact).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: A.attention_reference(
            q, k, v, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5)


def test_flash_attention_debug_asserts_on_capped_logits():
    """Debug mode (kwarg or RTPU_ATTN_DEBUG) fails LOUDLY when a logit
    would be silently clamped by the whole-kv path's static cap —
    and stays quiet for in-range logits or the exact streaming path."""
    import jax
    import jax.numpy as jnp
    import pytest
    from ray_tpu.ops import attention as A

    rng = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (1, 1, 128, 64)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    # in-range logits: debug mode is silent
    A.flash_attention(q, k, v, causal=True, force_pallas=True,
                      interpret=True, block_q=64, block_k=64, debug=True)

    # blown-up logits on the capped fast path: loud failure
    with pytest.raises(FloatingPointError, match="_CAP_HI"):
        A.flash_attention(q * 100.0, k, v, causal=True,
                          force_pallas=True, interpret=True,
                          block_q=64, block_k=64, debug=True)

    # the exact streaming path has no cap — same inputs pass
    out = A.flash_attention(q * 100.0, k, v, causal=True,
                            force_pallas=True, interpret=True,
                            block_q=64, block_k=64, debug=True,
                            exact=True)
    assert np.isfinite(np.asarray(out)).all()


def test_ring_attention_matches_full(cpu_mesh8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from ray_tpu.ops.attention import attention_reference
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    devices = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devices), ("sp",))
    rng = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (1, 2, 64, 16)  # seq 64 over 4 devices = 16 local
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    ref = attention_reference(q, k, v, causal=False)
    out = ring_attention_sharded(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_causal_matches_full(cpu_mesh8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from ray_tpu.ops.attention import attention_reference
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    devices = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devices), ("sp",))
    rng = jax.random.PRNGKey(4)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (2, 2, 64, 16)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    ref = attention_reference(q, k, v, causal=True)
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
