"""Worker-lease fast lane (reference:
src/ray/core_worker/transport/normal_task_submitter.cc — the reference's
normal-task path is lease-based: the owner leases a worker from the
raylet and pushes tasks to it directly).

Here the lease lane sits beside the GCS-routed default: a no-dep
CPU-only task costs 2 messages total (owner->worker request, reply with
the result) instead of 6 across 3 processes.  These tests pin the
engagement, arbitration, and fallback semantics.
"""

import time

import pytest

import ray_tpu


@pytest.fixture
def one_cpu_cluster():
    ray_tpu.init(num_cpus=1, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _driver():
    from ray_tpu._private import worker as wmod
    return wmod._global_worker


def _lease_engaged(w) -> bool:
    """True when the ACTIVE lease lane holds a live lease: the native
    direct pool when RTPU_NATIVE_RPC is on and the pump loaded, the
    asyncio pool otherwise (both implement the same lease contract)."""
    dc = w._direct_client
    if dc is not None and dc.usable():
        return any(L.addr for pool in dc.pools.values() for L in pool)
    return any(L.addr for pool in w._worker_leases.values() for L in pool)


def test_lease_lane_engages_and_results_are_correct(one_cpu_cluster):
    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get(f.remote(1)) == 2
    deadline = time.time() + 10
    w = _driver()
    while time.time() < deadline and not _lease_engaged(w):
        ray_tpu.get(f.remote(0))
    assert _lease_engaged(w), \
        "lease never engaged for a qualifying CPU task"
    # correctness through the leased path, including app errors
    assert ray_tpu.get([f.remote(i) for i in range(50)]) == \
        [i * 2 for i in range(50)]

    @ray_tpu.remote
    def boom():
        raise ValueError("expected")

    with pytest.raises(Exception, match="expected"):
        ray_tpu.get(boom.remote())
    # and still correct afterwards
    assert ray_tpu.get(f.remote(21)) == 42


def test_lease_skips_custom_resource_tasks(one_cpu_cluster):
    """Custom resources imply node placement — they must ride the
    normal scheduler path (the round-5 regression: a nodeB-only task
    parked forever on a local lease acquisition)."""
    @ray_tpu.remote
    def f():
        return "ok"

    w = _driver()
    spec = {"resources": {"CPU": 1.0, "nodeB": 1.0}}
    assert not w._lease_qualifies(spec)
    assert w._lease_qualifies({"resources": {"CPU": 1.0}})
    assert not w._lease_qualifies({"resources": {"CPU": 1.0},
                                   "plasma_deps": ["ab"]})
    assert not w._lease_qualifies({"resources": {"TPU": 1.0}})


def test_idle_lease_releases_capacity(one_cpu_cluster):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(10)])
    w = _driver()
    deadline = time.time() + 15
    while time.time() < deadline and _lease_engaged(w):
        time.sleep(0.25)
    assert not _lease_engaged(w), "idle lease still pinning capacity"
    # capacity is back: a fresh non-leasable task can run
    @ray_tpu.remote(max_retries=0)
    def g():
        return 2

    assert ray_tpu.get(
        g.options(scheduling_strategy="SPREAD").remote(), timeout=30) == 2


def test_cancel_reaches_leased_tasks(one_cpu_cluster):
    """cancel() must work for tasks the raylet never saw (pushed
    directly to a leased worker, or still parked driver-side)."""
    @ray_tpu.remote
    def quick():
        return 1

    ray_tpu.get([quick.remote() for _ in range(5)])  # lease engages

    @ray_tpu.remote
    def slow():
        time.sleep(30)
        return "finished"

    ref = slow.remote()
    time.sleep(0.5)  # let it start (or park) through the lease lane
    ray_tpu.cancel(ref)
    with pytest.raises(Exception):  # TaskCancelledError (or worker kill)
        ray_tpu.get(ref, timeout=25)


def test_mixed_workload_not_starved_by_leases(one_cpu_cluster):
    """With every CPU leased, a non-qualifying task must still run —
    the raylet revokes a lease under contention."""
    @ray_tpu.remote
    def fast(x):
        return x

    # keep the lease lane hot
    ray_tpu.get([fast.remote(i) for i in range(20)])

    @ray_tpu.remote
    def other():
        return "ran"

    # SPREAD strategy disqualifies the task from leasing, so it needs
    # real (non-leased) capacity -> the raylet must revoke
    ref = other.options(scheduling_strategy="SPREAD").remote()
    assert ray_tpu.get(ref, timeout=60) == "ran"


def test_disconnect_with_multiple_leases_refunds_all():
    """Regression (round-5 ADVICE high-severity): an owner disconnecting
    while holding 2+ leases must refund EVERY lease — _on_disconnect
    used to iterate conn.meta['leases'] while _release_lease pruned it
    in place, skipping every other entry and leaking its capacity
    forever."""
    import ray_tpu
    from ray_tpu._private import protocol

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        w = _driver()
        raylet_tcp = next(n["raylet_address"] for n in ray_tpu.nodes()
                          if n["alive"])
        # a second "owner": raw connection that takes 2 leases and dies
        conn = w.io.run(protocol.connect(raylet_tcp))
        grants = []
        for _ in range(2):
            r = w.call_sync(conn, "lease_worker",
                            {"resources": {"CPU": 1.0}}, timeout=60)
            assert "lease_id" in r, r
            grants.append(r["lease_id"])
        info = w.call_sync(w.raylet, "get_info", {})
        assert info["available"].get("CPU", 0) == 0  # both CPUs leased
        w.io.run(conn.aclose())  # owner dies holding both leases
        deadline = time.time() + 15
        cpu_avail = -1.0
        while time.time() < deadline:
            info = w.call_sync(w.raylet, "get_info", {})
            cpu_avail = info["available"].get("CPU", 0)
            if cpu_avail == info["resources"].get("CPU"):
                break
            time.sleep(0.2)
        assert cpu_avail == info["resources"].get("CPU"), \
            f"leaked lease capacity: available CPU {cpu_avail} after " \
            f"owner disconnect (leases={grants})"
        # and the refunded capacity is actually usable
        @ray_tpu.remote(num_cpus=2)
        def big():
            return "ok"

        assert ray_tpu.get(big.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()
