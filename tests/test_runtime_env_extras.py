"""Conda/container runtime envs + FastAPI-style Serve ingress.

Reference analogues: _private/runtime_env/conda.py (content-addressed
conda envs, gated on the binary), runtime_env/container.py (podman-
wrapped workers), serve/api.py @serve.ingress(app). The conda and
container runtimes aren't installed in this image, so the tests drive
the gates with fake binaries — exactly how the GCE provider tests
inject a fake transport.
"""

import json
import os
import stat
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import runtime_env as renv


# ------------------------------------------------------------------ conda

def test_conda_gated_when_missing(tmp_path, monkeypatch):
    monkeypatch.delenv("CONDA_EXE", raising=False)
    monkeypatch.setenv("PATH", str(tmp_path))  # no conda anywhere
    with pytest.raises(RuntimeError, match="conda install"):
        renv._ensure_conda_env("myenv", str(tmp_path))


def test_conda_named_and_dict_envs(tmp_path, monkeypatch):
    """A fake conda binary proves both resolution paths: named envs
    resolve under `conda info --base`, dict specs materialize a
    content-addressed env exactly once."""
    base = tmp_path / "conda_base"
    envdir = base / "envs" / "myenv" / "bin"
    envdir.mkdir(parents=True)
    (envdir / "python").write_text("")
    fake = tmp_path / "conda"
    fake.write_text(f"""#!/bin/sh
case "$1" in
  info) echo {base} ;;
  env)  # conda env create -p <dir> -f <yml> --yes
        mkdir -p "$4/bin" && : > "$4/bin/python" ;;
esac
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("CONDA_EXE", str(fake))

    py = renv._ensure_conda_env("myenv", str(tmp_path / "cache"))
    assert py == str(envdir / "python")
    with pytest.raises(RuntimeError, match="not found"):
        renv._ensure_conda_env("missing-env", str(tmp_path / "cache"))

    spec = {"dependencies": ["pip", {"pip": ["six"]}]}
    py2 = renv._ensure_conda_env(spec, str(tmp_path / "cache"))
    assert os.path.exists(py2)
    # second call hits the .ready marker (no re-create): drop the fake
    # binary's exec bit to prove conda isn't invoked again
    assert renv._ensure_conda_env(spec, str(tmp_path / "cache")) == py2


def test_conda_env_dir_passthrough(tmp_path, monkeypatch):
    monkeypatch.setenv("CONDA_EXE", "/bin/sh")  # exists; unused
    d = tmp_path / "someenv"
    (d / "bin").mkdir(parents=True)
    assert renv._ensure_conda_env(str(d), str(tmp_path)) == \
        str(d / "bin" / "python")


# -------------------------------------------------------------- container

def test_container_command_shape(tmp_path, monkeypatch):
    monkeypatch.setenv("RTPU_CONTAINER_RUNTIME", "/usr/bin/fakectr")
    cmd = renv.container_command(
        {"image": "img:1", "run_options": ["--gpus=none"]},
        "/sess", "/cache", env_keys=["RTPU_NODE_ID"])
    assert cmd[0] == "/usr/bin/fakectr"
    assert cmd[-1] == "img:1"
    assert "-v" in cmd and "/sess:/sess" in cmd
    assert cmd[cmd.index("-e") + 1] == "RTPU_NODE_ID"
    assert "--gpus=none" in cmd
    with pytest.raises(RuntimeError, match="image"):
        renv.container_command({}, "/s", "/c")
    monkeypatch.delenv("RTPU_CONTAINER_RUNTIME")
    monkeypatch.setenv("PATH", str(tmp_path))
    with pytest.raises(RuntimeError, match="podman or docker"):
        renv.container_command({"image": "x"}, "/s", "/c")


def test_container_worker_end_to_end(tmp_path):
    """A fake container runtime (drops the wrapper args, execs the
    worker command) proves the raylet's containerized spawn path: the
    task really runs behind the runtime prefix."""
    fake = tmp_path / "fakectr"
    fake.write_text("""#!/bin/sh
while [ "$1" != "TESTIMG" ]; do shift; done
shift
export RTPU_RAN_IN_CONTAINER=1
exec "$@"
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    os.environ["RTPU_CONTAINER_RUNTIME"] = str(fake)
    try:
        ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                     object_store_memory=64 * 1024 * 1024)

        @ray_tpu.remote(runtime_env={"container": {"image": "TESTIMG"}})
        def probe():
            return os.environ.get("RTPU_RAN_IN_CONTAINER")

        assert ray_tpu.get(probe.remote(), timeout=60) == "1"
    finally:
        os.environ.pop("RTPU_CONTAINER_RUNTIME", None)
        ray_tpu.shutdown()


def test_conda_worker_end_to_end(tmp_path):
    """A fake conda that materializes envs whose bin/python symlinks the
    real interpreter proves the full spawn path: env creation happens
    ONCE (cache), the worker launches through the env's python, and
    same-env tasks reuse the pooled worker."""
    import sys

    calls = tmp_path / "create_calls"
    fake = tmp_path / "conda"
    # the fake env's bin/python is an exec WRAPPER around the real
    # interpreter (a symlink would lose the venv's pyvenv.cfg context)
    # that stamps the env dir into the worker's environment
    fake.write_text(f"""#!/bin/sh
case "$1" in
  info) echo {tmp_path}/conda_base ;;
  env)  echo created >> {calls}
        mkdir -p "$4/bin"
        printf '#!/bin/sh\\nexport RTPU_FAKE_CONDA_ENV="%s"\\nexec {sys.executable} "$@"\\n' "$4" > "$4/bin/python"
        chmod +x "$4/bin/python" ;;
esac
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    os.environ["CONDA_EXE"] = str(fake)
    try:
        ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                     object_store_memory=64 * 1024 * 1024)
        # unique spec per run: the conda cache is content-addressed and
        # host-wide, so a fixed spec would reuse an env materialized by
        # a PREVIOUS test run's fake
        import uuid
        spec = {"dependencies": [f"python=3  # {uuid.uuid4().hex}"]}

        @ray_tpu.remote(runtime_env={"conda": spec})
        def probe():
            return os.environ.get("RTPU_FAKE_CONDA_ENV"), os.getpid()

        env1, pid1 = ray_tpu.get(probe.remote(), timeout=120)
        assert env1 and "/conda/" in env1  # launched through the env
        # same env -> same materialized env dir and NO second env
        # create (the content-addressed cache; the pid may differ —
        # the pool can hold several same-env workers)
        env2, pid2 = ray_tpu.get(probe.remote(), timeout=120)
        assert env2 == env1
        assert calls.read_text().count("created") == 1
    finally:
        os.environ.pop("CONDA_EXE", None)
        ray_tpu.shutdown()


# ---------------------------------------------------------------- ingress

def test_api_router_dispatch_unit():
    app = serve.APIRouter()

    class Svc:
        scale = 10

        @app.get("/items/{item_id}")
        def get_item(self, item_id: int):
            return {"id": item_id, "scaled": item_id * self.scale}

        @app.post("/items")
        def create(self, body):
            return {"created": body}

    from ray_tpu.serve.ingress import _dispatch
    svc = Svc()
    out = _dispatch(svc, app.routes, "/items/7", "GET", None)
    assert out == {"id": 7, "scaled": 70}
    out = _dispatch(svc, app.routes, "/items", "POST", [1, 2])
    assert out == {"created": [1, 2]}
    with pytest.raises(LookupError, match="405"):
        _dispatch(svc, app.routes, "/items/7", "DELETE", None)
    with pytest.raises(LookupError, match="404"):
        _dispatch(svc, app.routes, "/nope", "GET", None)


def test_serve_ingress_http_end_to_end():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                 object_store_memory=64 * 1024 * 1024)
    try:
        app = serve.APIRouter()

        @serve.deployment
        @serve.ingress(app)
        class Calc:
            def __init__(self):
                self.base = 100

            @app.get("/add/{x}")
            def add(self, x: int):
                return {"sum": self.base + x}

            @app.post("/mul")
            def mul(self, factor):
                return {"product": self.base * factor}

        serve.run(Calc.bind(), route_prefix="/calc", http_port=8155)
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        port = ray_tpu.get(proxy.get_port.remote())

        got = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/calc/add/23", timeout=30).read())
        assert got == {"sum": 123}

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/calc/mul", data=b"7",
            headers={"Content-Type": "application/json"})
        got = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert got == {"product": 700}

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/calc/nope", timeout=30)
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/calc/add/1", data=b"{}",
                headers={"Content-Type": "application/json"}),
                timeout=30)
        assert ei.value.code == 405
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
