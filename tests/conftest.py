"""Test fixtures.

Reference analogue: python/ray/tests/conftest.py (ray_start_regular:245,
ray_start_cluster:326). JAX tests run on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count) so multi-chip sharding logic is
exercised without TPU hardware (SURVEY.md environment notes).
"""

import os

# Must be set before jax initializes a backend anywhere in the test process.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RTPU_PRESTART_WORKERS", "0")
# Every inbound RPC in every test process is validated against the
# declared wire schema (_private/schema.py) — handler/schema drift
# fails loudly here instead of silently skewing the protocol.
os.environ.setdefault("RTPU_VALIDATE_WIRE", "1")
# Full head-sampling in tests: production defaults to 10% (Dapper
# stance, bounds serve overhead — see _private/tracing.py), but tests
# assert on complete span trees for specific request ids.
os.environ.setdefault("RTPU_TRACE_SAMPLE", "1.0")

# Tune writes experiment dirs (loggers + resumable state) to this root by
# default; keep test runs out of $HOME.
import tempfile  # noqa: E402
os.environ.setdefault(
    "RTPU_RESULTS_DIR", tempfile.mkdtemp(prefix="rtpu_results_"))

# The axon sitecustomize imports jax before this conftest runs, so the env
# var alone is too late — force the platform through the live config (safe
# as long as no backend has been initialized yet).
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # tests compare kernel numerics against XLA references: keep f32 matmuls
    jax.config.update("jax_default_matmul_precision", "highest")
except Exception:
    pass

import pytest  # noqa: E402


def pytest_sessionstart(session):
    """Pin the heavyweight integration deps as REQUIRED: the
    torch/transformers-gated tests (test_llama, test_transformers_*,
    lightning/gbdt adapters) importorskip — on a leaner image the
    breadth they prove would silently evaporate as skips.  Set
    RTPU_ALLOW_MISSING_DEPS=1 to opt back into skipping."""
    if os.environ.get("RTPU_ALLOW_MISSING_DEPS"):
        return
    import importlib.util
    missing = []
    # the deps this image ships and the breadth tests rely on
    # (xgboost/lightgbm are NOT in the image — their trainers gate on
    # them by design and fall back to sklearn GBDT)
    for dep in ("torch", "transformers", "sklearn"):
        if importlib.util.find_spec(dep) is None:
            missing.append(dep)
    if missing:
        raise pytest.UsageError(
            f"required integration deps missing: {missing} — the gated "
            "tests would silently skip; install them or set "
            "RTPU_ALLOW_MISSING_DEPS=1 to accept reduced coverage")


@pytest.fixture(scope="session", autouse=True)
def _no_asyncio_teardown_leaks():
    """Regression gate for shutdown hygiene: a Connection/EventLoopThread
    that abandons pending tasks surfaces here as "Task was destroyed but
    it is pending!" (Task.__del__ -> asyncio logger) or "Event loop is
    closed" callbacks.  Zero tolerance — these mask real errors in every
    long-lived process log."""
    import gc
    import logging

    leaked = []

    class _Trap(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            # "Event loop is closed" rides in exc_info (the default
            # asyncio exception handler logs "Exception in callback ..."
            # with the RuntimeError attached), not the message text.
            if record.exc_info and record.exc_info[1] is not None:
                msg += f" | {record.exc_info[1]!r}"
            if ("Task was destroyed but it is pending" in msg
                    or "Event loop is closed" in msg):
                leaked.append(msg)

    trap = _Trap()
    logging.getLogger("asyncio").addHandler(trap)
    yield
    gc.collect()  # force pending Task.__del__ before we assert
    logging.getLogger("asyncio").removeHandler(trap)
    assert not leaked, (
        f"{len(leaked)} asyncio teardown leak(s); first 5: {leaked[:5]}")


@pytest.fixture(scope="function")
def ray_start_regular():
    import ray_tpu
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def ray_start_shared():
    """Module-scoped cluster for cheap tests (worker startup is ~1s/proc on
    the 1-core CI box, so most tests share one cluster)."""
    import ray_tpu
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture(scope="function")
def ray_start_cluster():
    from ray_tpu._private.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    yield cluster
    cluster.shutdown()


@pytest.fixture
def cpu_mesh8():
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest must force 8 host devices"
    return Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "tp"))
