"""Multi-agent RLlib, evaluation workers, connectors.

Reference analogues: rllib/tests/test_multi_agent_env.py,
test_evaluation.py (eval WorkerSet), connectors tests.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.env import Box


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_multiagent_env_api():
    from ray_tpu.rllib.env import MultiAgentCartPole
    env = MultiAgentCartPole({"num_agents": 2})
    obs, infos = env.reset(seed=0)
    assert set(obs) == {"agent_0", "agent_1"}
    obs, rews, terms, truncs, infos = env.step(
        {"agent_0": 0, "agent_1": 1})
    assert set(rews) == {"agent_0", "agent_1"}
    assert "__all__" in terms


def test_multiagent_worker_sample_batches():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig, PPOPolicy
    from ray_tpu.rllib.rollout_worker import MultiAgentRolloutWorker
    from ray_tpu.rllib.sample_batch import MultiAgentBatch

    config = (PPOConfig().environment(
        "MultiAgentCartPole", env_config={"num_agents": 2})
        .rollouts(rollout_fragment_length=32)
        .multi_agent(
            policies={"pol_a": {}, "pol_b": {}},
            policy_mapping_fn=lambda aid: "pol_a"
            if aid == "agent_0" else "pol_b")
        .debugging(seed=0)).to_dict()
    w = MultiAgentRolloutWorker(config, PPOPolicy)
    batch = w.sample()
    assert isinstance(batch, MultiAgentBatch)
    assert set(batch.policy_batches) == {"pol_a", "pol_b"}
    assert batch.env_steps() == 32
    # both agents act until their own episode ends (an early-terminated
    # agent sits out until "__all__"), so agent steps land in (32, 64]
    assert 32 < batch.agent_steps() <= 64
    # PPO postprocessing ran per trajectory (GAE columns present)
    for b in batch.policy_batches.values():
        assert "advantages" in b


def test_multiagent_ppo_two_policies_learn(cluster):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment(
        "MultiAgentCartPole", env_config={"num_agents": 2})
        .rollouts(num_workers=0, rollout_fragment_length=64)
        .training(train_batch_size=512, sgd_minibatch_size=128,
                  num_sgd_iter=6, lr=4e-3)
        .multi_agent(
            policies={"pol_a": {}, "pol_b": {}},
            policy_mapping_fn=lambda aid: "pol_a"
            if aid == "agent_0" else "pol_b")
        .debugging(seed=1).build())
    best = 0.0
    for _ in range(30):
        r = algo.step()
        assert "info" in r and "learner" in r["info"]
        if not np.isnan(r["episode_reward_mean"]):
            best = max(best, r["episode_reward_mean"])
        if best > 120:  # sum over both agents; random is ~40
            break
    learner_info = r["info"]["learner"]
    assert set(learner_info) <= {"pol_a", "pol_b"}
    assert len(learner_info) == 2
    algo.cleanup()
    assert best > 120, f"multi-agent PPO stuck at {best}"


def test_multiagent_checkpoint_roundtrip(cluster):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment(
        "MultiAgentCartPole", env_config={"num_agents": 2})
        .rollouts(rollout_fragment_length=16)
        .training(train_batch_size=32, sgd_minibatch_size=16,
                  num_sgd_iter=1)
        .multi_agent(policies={"pol_a": {}, "pol_b": {}},
                     policy_mapping_fn=lambda aid: "pol_a"
                     if aid == "agent_0" else "pol_b")
        .debugging(seed=0).build())
    algo.step()
    state = algo.save_checkpoint()
    w_before = algo.get_policy("pol_a").get_weights()
    algo2 = (PPOConfig().environment(
        "MultiAgentCartPole", env_config={"num_agents": 2})
        .multi_agent(policies={"pol_a": {}, "pol_b": {}},
                     policy_mapping_fn=lambda aid: "pol_a"
                     if aid == "agent_0" else "pol_b")
        .debugging(seed=99).build())
    algo2.load_checkpoint(state)
    w_after = algo2.get_policy("pol_a").get_weights()
    leaves_a = [np.asarray(x) for x in
                __import__("jax").tree_util.tree_leaves(w_before)]
    leaves_b = [np.asarray(x) for x in
                __import__("jax").tree_util.tree_leaves(w_after)]
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(a, b)
    algo.cleanup()
    algo2.cleanup()


def test_evaluation_workers(cluster):
    from ray_tpu.rllib.algorithms.pg import PGConfig
    algo = (PGConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=2, rollout_fragment_length=32)
            .training(train_batch_size=64)
            .evaluation(evaluation_interval=2,
                        evaluation_num_episodes=4,
                        evaluation_num_workers=1)
            .debugging(seed=0).build())
    assert algo.evaluation_workers is not None
    r1 = algo.step()
    assert "evaluation" not in r1  # interval=2
    r2 = algo.step()
    assert "evaluation" in r2
    ev = r2["evaluation"]
    assert ev["episodes_this_eval"] >= 4
    assert ev["episode_reward_mean"] > 0
    algo.cleanup()


def test_connectors_pipeline_unit():
    from ray_tpu.rllib.connectors import (ClipActionConnector,
                                          ConnectorPipeline,
                                          FlattenObsConnector,
                                          MeanStdObsConnector)
    p = ConnectorPipeline([FlattenObsConnector()])
    out = p(np.zeros((4, 2, 3)))
    assert out.shape == (4, 6)
    clip = ClipActionConnector(-1.0, 1.0)
    np.testing.assert_allclose(clip(np.array([-3.0, 0.5, 9.0])),
                               [-1.0, 0.5, 1.0])
    ms = MeanStdObsConnector()
    rng = np.random.default_rng(0)
    data = rng.normal(5.0, 2.0, (100, 3))
    for i in range(0, 100, 10):
        out = ms(data[i:i + 10])
    # after enough samples the running normalization centers the data
    assert abs(out.mean()) < 0.5
    # state round-trips
    st = ms.state()
    ms2 = MeanStdObsConnector()
    ms2.set_state(st)
    np.testing.assert_allclose(ms2(data[:10]), ms(data[:10]), atol=1e-5)


def test_connectors_in_rollout_worker(cluster):
    from ray_tpu.rllib.algorithms.pg import PGConfig
    from ray_tpu.rllib.connectors import MeanStdObsConnector

    algo = (PGConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=2, rollout_fragment_length=32)
            .training(train_batch_size=64)
            .update_from_dict(
                {"connectors": {"obs": [MeanStdObsConnector()]}})
            .debugging(seed=0).build())
    w = algo.workers.local_worker
    batch = w.sample()
    # the policy saw normalized observations
    assert abs(float(np.mean(batch["obs"]))) < 1.0
    assert float(np.std(batch["obs"])) < 5.0
    algo.cleanup()
