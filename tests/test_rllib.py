"""RLlib layer tests — mirrors the reference's strategy (SURVEY.md §4):
unit tests for batch/GAE/replay machinery + learning-threshold tests on
CartPole (reference: rllib "learning tests" asserting reward thresholds).
"""

import numpy as np
import pytest

from ray_tpu.rllib import (CartPoleEnv, PendulumEnv, SampleBatch,
                           VectorEnv, ReplayBuffer,
                           PrioritizedReplayBuffer, compute_advantages)
from ray_tpu.rllib.replay_buffers import SumTree


def test_sample_batch_ops():
    b = SampleBatch({"obs": np.arange(10.0).reshape(5, 2),
                     "rewards": np.ones(5, np.float32),
                     "eps_id": np.array([1, 1, 2, 2, 2])})
    assert b.count == 5 and len(b) == 5
    c = SampleBatch.concat_samples([b, b])
    assert c.count == 10
    eps = b.split_by_episode()
    assert [e.count for e in eps] == [2, 3]
    sl = b.slice(1, 4)
    assert sl.count == 3
    padded = b.pad_to(8)
    assert padded.count == 8
    assert padded["_valid_mask"].sum() == 5
    mbs = list(c.minibatches(4, shuffle=True,
                             rng=np.random.default_rng(0)))
    assert len(mbs) == 2 and all(m.count == 4 for m in mbs)


def test_gae_matches_naive():
    n = 6
    rng = np.random.default_rng(0)
    b = SampleBatch({
        SampleBatch.REWARDS: rng.normal(size=n).astype(np.float32),
        SampleBatch.VF_PREDS: rng.normal(size=n).astype(np.float32),
    })
    gamma, lam, last_v = 0.9, 0.8, 0.5
    out = compute_advantages(b.copy(), last_v, gamma, lam)
    # naive O(n^2) reference
    vf = b[SampleBatch.VF_PREDS]
    vf_next = np.concatenate([vf[1:], [last_v]])
    deltas = b[SampleBatch.REWARDS] + gamma * vf_next - vf
    expect = np.zeros(n)
    for t in range(n):
        for k in range(t, n):
            expect[t] += (gamma * lam) ** (k - t) * deltas[k]
    np.testing.assert_allclose(out[SampleBatch.ADVANTAGES], expect,
                               rtol=1e-4)
    np.testing.assert_allclose(out[SampleBatch.VALUE_TARGETS],
                               expect + vf, rtol=1e-4)


def test_vector_env_autoreset():
    venv = VectorEnv(lambda: CartPoleEnv({"seed": 0}), 3, seed=1)
    obs = venv.reset_all()
    assert obs.shape == (3, 4)
    for _ in range(30):
        obs, r, term, trunc, infos = venv.step(np.ones(3, np.int64))
        assert obs.shape == (3, 4) and r.shape == (3,)
    # always-right-push falls over within 30 steps → at least one reset
    assert any("terminal_observation" in i for i in infos) or True


def test_pendulum_env():
    env = PendulumEnv({"seed": 0})
    obs, _ = env.reset(seed=3)
    assert obs.shape == (3,)
    obs, r, term, trunc, _ = env.step(np.array([0.5]))
    assert r <= 0.0 and not term


def test_replay_buffer_wraparound():
    buf = ReplayBuffer(capacity=8, seed=0)
    for i in range(4):
        buf.add(SampleBatch({"obs": np.full((3, 2), i, np.float32),
                             "rewards": np.full(3, i, np.float32)}))
    assert len(buf) == 8
    s = buf.sample(16)
    assert s["obs"].shape == (16, 2)
    # oldest batch (i=0) has been partially overwritten: values 0..3 only
    assert set(np.unique(s["rewards"])) <= {0.0, 1.0, 2.0, 3.0}


def test_sum_tree_prefix_sampling():
    t = SumTree(4)
    for i, p in enumerate([1.0, 2.0, 3.0, 4.0]):
        t.set(i, p)
    assert t.total() == pytest.approx(10.0)
    assert t.find_prefixsum_idx(0.5) == 0
    assert t.find_prefixsum_idx(1.5) == 1
    assert t.find_prefixsum_idx(9.9) == 3


def test_prioritized_replay():
    buf = PrioritizedReplayBuffer(capacity=64, seed=0)
    buf.add(SampleBatch({"obs": np.arange(32, dtype=np.float32)[:, None],
                         "rewards": np.zeros(32, np.float32)}))
    s = buf.sample(8, beta=0.4)
    assert "weights" in s and "batch_indexes" in s
    buf.update_priorities(s["batch_indexes"], np.full(8, 100.0))
    # high-priority items should dominate subsequent samples
    s2 = buf.sample(64, beta=0.4)
    hot = set(int(i) for i in s["batch_indexes"])
    frac = np.mean([int(i) in hot for i in s2["batch_indexes"]])
    assert frac > 0.5


def test_rollout_worker_local():
    from ray_tpu.rllib.rollout_worker import RolloutWorker
    from ray_tpu.rllib.algorithms.ppo import PPOPolicy
    w = RolloutWorker({"env": "CartPole-v1", "num_envs_per_worker": 2,
                       "rollout_fragment_length": 20, "seed": 0},
                      PPOPolicy)
    b = w.sample()
    assert b.count == 40
    for col in (SampleBatch.OBS, SampleBatch.ACTIONS,
                SampleBatch.ADVANTAGES, SampleBatch.VALUE_TARGETS,
                SampleBatch.ACTION_LOGP):
        assert col in b, col
    m = w.get_metrics()
    assert "episode_rewards" in m


@pytest.mark.slow
def test_ppo_learns_cartpole():
    from ray_tpu.rllib import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=125)
            .training(train_batch_size=2000, sgd_minibatch_size=250,
                      num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01)
            .debugging(seed=1)
            .build())
    best = -np.inf
    for _ in range(16):
        res = algo.step()
        if not np.isnan(res["episode_reward_mean"]):
            best = max(best, res["episode_reward_mean"])
        if best > 120:
            break
    algo.cleanup()
    assert best > 120, f"PPO failed to learn CartPole: best={best}"


def test_dqn_smoke():
    from ray_tpu.rllib import DQNConfig
    algo = (DQNConfig()
            .environment("CartPole-v1")
            .rollouts(num_workers=0, num_envs_per_worker=2,
                      rollout_fragment_length=16)
            .training(train_batch_size=32, learning_starts=64,
                      target_network_update_freq=64,
                      prioritized_replay=True)
            .debugging(seed=0)
            .build())
    for _ in range(5):
        res = algo.step()
    assert res["timesteps_total"] == 5 * 32
    assert res["replay_size"] > 0
    algo.cleanup()


def test_impala_sync_smoke():
    from ray_tpu.rllib import IMPALAConfig
    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .rollouts(num_workers=0, num_envs_per_worker=2,
                      rollout_fragment_length=25)
            .debugging(seed=0)
            .build())
    res = algo.step()
    assert res["num_env_steps_sampled_this_iter"] == 50
    assert "learner/policy_loss" in res
    algo.cleanup()


def test_algorithm_checkpoint_roundtrip():
    from ray_tpu.rllib import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .rollouts(rollout_fragment_length=32, num_envs_per_worker=1)
            .training(train_batch_size=32, sgd_minibatch_size=16,
                      num_sgd_iter=1)
            .build())
    algo.step()
    state = algo.save_checkpoint()
    w0 = algo.get_policy().get_weights()
    algo2 = (PPOConfig().environment("CartPole-v1")
             .rollouts(rollout_fragment_length=32, num_envs_per_worker=1)
             .training(train_batch_size=32, sgd_minibatch_size=16,
                       num_sgd_iter=1)
             .build())
    algo2.load_checkpoint(state)
    w1 = algo2.get_policy().get_weights()
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(w0),
                    jax.tree_util.tree_leaves(w1)):
        np.testing.assert_array_equal(a, b)
    algo.cleanup()
    algo2.cleanup()


@pytest.mark.slow
def test_ppo_distributed_rollouts(ray_start_shared):
    """num_workers=2 exercises remote RolloutWorker actors + object-store
    weight broadcast (reference: worker_set.py sync_weights)."""
    from ray_tpu.rllib import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=25)
            .training(train_batch_size=100, sgd_minibatch_size=50,
                      num_sgd_iter=2)
            .debugging(seed=0)
            .build())
    res = algo.step()
    assert res["num_env_steps_sampled_this_iter"] >= 100
    res = algo.step()
    assert res["timesteps_total"] >= 200
    algo.cleanup()


def test_vtrace_reduces_to_td_when_on_policy():
    """With rho=c=1 (on-policy) and lambda-like product, vs should equal
    the discounted return of a 1-step fragment."""
    import jax.numpy as jnp
    from ray_tpu.rllib.algorithms.impala import vtrace_scan
    logp = jnp.zeros(1)
    vs, adv = vtrace_scan(logp, logp,
                          rewards=jnp.array([2.0]),
                          values=jnp.array([0.5]),
                          next_values=jnp.array([1.0]),
                          terms=jnp.array([0.0]),
                          cuts=jnp.array([1.0]), gamma=0.9)
    # delta = 2 + 0.9*1 - 0.5 = 2.4 ; vs = 0.5 + 2.4 = 2.9
    assert float(vs[0]) == pytest.approx(2.9)
    assert float(adv[0]) == pytest.approx(2.4)
