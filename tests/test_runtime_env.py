"""Runtime environment materialization tests.

Reference analogue: python/ray/tests/test_runtime_env*.py over
_private/runtime_env/{pip,packaging}.py + runtime_env_agent. Covers
env_vars, packaged working_dir, py_modules, and pip venv isolation (a
locally-built wheel the driver does NOT have installed).
"""

import os
import zipfile

import pytest

import ray_tpu


@pytest.fixture(scope="function")
def ray_env_cluster():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                       object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _build_test_wheel(dirpath, name="rtpu_testpkg", version="0.1"):
    """A minimal pure-python wheel, built offline with zipfile."""
    whl = os.path.join(dirpath, f"{name}-{version}-py3-none-any.whl")
    di = f"{name}-{version}.dist-info"
    files = {
        f"{name}/__init__.py": "MAGIC = 'wheel-installed-7791'\n",
        f"{di}/METADATA": (f"Metadata-Version: 2.1\nName: {name}\n"
                           f"Version: {version}\n"),
        f"{di}/WHEEL": ("Wheel-Version: 1.0\nGenerator: test\n"
                        "Root-Is-Purelib: true\nTag: py3-none-any\n"),
    }
    record_lines = [f"{p},," for p in files] + [f"{di}/RECORD,,"]
    files[f"{di}/RECORD"] = "\n".join(record_lines) + "\n"
    with zipfile.ZipFile(whl, "w") as zf:
        for path, content in files.items():
            zf.writestr(path, content)
    return whl


def test_env_vars(ray_env_cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "on-42"}})
    def read_env():
        return os.environ.get("RTPU_TEST_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "on-42"


def test_working_dir_packaged(ray_env_cluster, tmp_path):
    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "data.txt").write_text("packaged-working-dir-99")
    (wd / "helper.py").write_text("def val():\n    return 'from-helper'\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def read_file():
        import helper  # importable: cwd is the extracted package
        with open("data.txt") as f:
            return f.read(), helper.val()

    data, helped = ray_tpu.get(read_file.remote(), timeout=90)
    assert data == "packaged-working-dir-99"
    assert helped == "from-helper"


def test_py_modules(ray_env_cluster, tmp_path):
    mod = tmp_path / "sidecar_mod"
    mod.mkdir()
    (mod / "__init__.py").write_text("ANSWER = 1234\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def use_mod():
        import sidecar_mod
        return sidecar_mod.ANSWER

    assert ray_tpu.get(use_mod.remote(), timeout=90) == 1234


def test_pip_env_isolation(ray_env_cluster, tmp_path):
    whl = _build_test_wheel(str(tmp_path))

    # the driver does NOT have the package
    with pytest.raises(ImportError):
        import rtpu_testpkg  # noqa: F401

    @ray_tpu.remote(runtime_env={"pip": [whl]})
    def in_env():
        import rtpu_testpkg
        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(in_env.remote(), timeout=120) == "wheel-installed-7791"
