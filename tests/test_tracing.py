"""Tracing + on-demand profiling (reference:
util/tracing/tracing_helper.py span propagation through TaskSpecs and
dashboard/modules/reporter/profile_manager.py live worker profiling)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_trace_tree_renders_in_timeline(cluster):
    """driver → parent task → child task must appear in the merged
    chrome timeline as a linked span tree (the verdict's done-bar)."""

    @ray_tpu.remote
    def tr_child(x):
        return x + 1

    @ray_tpu.remote
    def tr_parent(x):
        return ray_tpu.get(tr_child.remote(x)) + 10

    assert ray_tpu.get(tr_parent.remote(5)) == 16
    # the worker flusher pushes buffers to the GCS every ~1s
    deadline = time.monotonic() + 15
    parent_ev = child_ev = None
    while time.monotonic() < deadline:
        evs = [e for e in ray_tpu.timeline()
               if e.get("cat") == "task"
               and (e.get("args") or {}).get("trace_id")]
        parents = [e for e in evs if e["name"] == "tr_parent"]
        children = [e for e in evs if e["name"] == "tr_child"]
        if parents and children:
            parent_ev, child_ev = parents[-1], children[-1]
            break
        time.sleep(0.5)
    assert parent_ev is not None and child_ev is not None, \
        "trace-tagged task events never reached the merged timeline"
    pa, ca = parent_ev["args"], child_ev["args"]
    # one trace; the child's parent span is the parent task's span;
    # the parent's own parent is the driver root
    assert pa["trace_id"] == ca["trace_id"]
    assert ca["parent_span_id"] == pa["span_id"]
    assert pa["parent_span_id"] == "root"


def test_trace_ctx_rides_batched_submissions(cluster):
    @ray_tpu.remote
    def tb_noop(i):
        return i

    refs = tb_noop.remote_batch([(i,) for i in range(4)])
    assert ray_tpu.get(refs) == [0, 1, 2, 3]
    deadline = time.monotonic() + 15
    evs = []
    while time.monotonic() < deadline:
        evs = [e for e in ray_tpu.timeline()
               if e["name"] == "tb_noop"
               and (e.get("args") or {}).get("span_id")]
        if len(evs) >= 4:
            break
        time.sleep(0.5)
    assert len(evs) >= 4
    spans = {e["args"]["span_id"] for e in evs}
    assert len(spans) >= 4  # every task got its own span
    assert all(e["args"]["parent_span_id"] == "root" for e in evs)


def test_profile_stacks_snapshots_live_worker(cluster):
    from ray_tpu.experimental.state.api import profile_stacks

    @ray_tpu.remote
    def ps_busy(sec):
        import time as _t
        _t.sleep(sec)
        return 1

    ref = ps_busy.remote(4.0)
    time.sleep(1.0)  # let it dispatch and block in sleep
    snap = profile_stacks()
    workers = [w for n in snap["nodes"] for w in n.get("workers", [])
               if "stacks" in w]
    assert workers, snap
    joined = "\n".join(w["stacks"] for w in workers)
    # the busy task's sleep frame is visible in some worker's stack
    assert "ps_busy" in joined or "_t.sleep" in joined or \
        "sleep" in joined, joined[:2000]
    busy = [w for w in workers if w.get("current_task")]
    assert busy, "no worker reported a current task"
    assert ray_tpu.get(ref, timeout=30) == 1


def test_profile_stacks_http_route(cluster):
    """The dashboard exposes the same snapshot over HTTP."""
    import json
    import urllib.request
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18271)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile/stacks",
            timeout=30) as resp:
        doc = json.loads(resp.read())
    assert "nodes" in doc


def test_flamegraph_of_busy_worker(cluster):
    """Timed sampling profile -> folded stacks: the busy function's
    frame dominates the samples (reference:
    reporter/profile_manager.py py-spy flamegraphs)."""
    import json
    import urllib.request
    from ray_tpu.dashboard.dashboard import start_dashboard

    @ray_tpu.remote
    def fg_spin(sec):
        import time as _t
        end = _t.monotonic() + sec
        acc = 0
        while _t.monotonic() < end:  # CPU-busy, stays on the stack
            acc += 1
        return acc

    ref = fg_spin.remote(6.0)
    time.sleep(1.0)  # let it dispatch
    port = start_dashboard(port=18272)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile/flamegraph"
            f"?duration_s=1.5", timeout=60) as resp:
        doc = json.loads(resp.read())
    profiles = [w for n in doc["nodes"] for w in n.get("workers", [])
                if w.get("folded")]
    assert profiles, doc
    joined = "\n".join(p["folded"] for p in profiles)
    assert "fg_spin" in joined, joined[:1500]
    # folded format: "frame;frame;... count" — flamegraph.pl-parseable
    line = next(ln for ln in joined.splitlines() if "fg_spin" in ln)
    assert line.rsplit(" ", 1)[1].isdigit()
    assert all(p["samples"] > 0 for p in profiles)
    assert ray_tpu.get(ref, timeout=60) > 0
