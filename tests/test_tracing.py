"""Tracing + on-demand profiling (reference:
util/tracing/tracing_helper.py span propagation through TaskSpecs and
dashboard/modules/reporter/profile_manager.py live worker profiling;
the span model / critical-path analyzer is docs/TRACING.md)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import tracing


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_trace_tree_renders_in_timeline(cluster):
    """driver → parent task → child task must appear in the merged
    chrome timeline as a linked span tree (the verdict's done-bar)."""

    @ray_tpu.remote
    def tr_child(x):
        return x + 1

    @ray_tpu.remote
    def tr_parent(x):
        return ray_tpu.get(tr_child.remote(x)) + 10

    assert ray_tpu.get(tr_parent.remote(5)) == 16
    # the worker flusher pushes buffers to the GCS every ~1s
    deadline = time.monotonic() + 15
    parent_ev = child_ev = None
    while time.monotonic() < deadline:
        evs = [e for e in ray_tpu.timeline()
               if e.get("cat") == "task"
               and (e.get("args") or {}).get("trace_id")]
        parents = [e for e in evs if e["name"] == "tr_parent"]
        children = [e for e in evs if e["name"] == "tr_child"]
        if parents and children:
            parent_ev, child_ev = parents[-1], children[-1]
            break
        time.sleep(0.5)
    assert parent_ev is not None and child_ev is not None, \
        "trace-tagged task events never reached the merged timeline"
    pa, ca = parent_ev["args"], child_ev["args"]
    # one trace; the child's parent span is the parent task's span;
    # the parent's own parent is the driver root
    assert pa["trace_id"] == ca["trace_id"]
    assert ca["parent_span_id"] == pa["span_id"]
    assert pa["parent_span_id"] == "root"


def test_trace_ctx_rides_batched_submissions(cluster):
    @ray_tpu.remote
    def tb_noop(i):
        return i

    refs = tb_noop.remote_batch([(i,) for i in range(4)])
    assert ray_tpu.get(refs) == [0, 1, 2, 3]
    deadline = time.monotonic() + 15
    evs = []
    while time.monotonic() < deadline:
        evs = [e for e in ray_tpu.timeline()
               if e["name"] == "tb_noop"
               and (e.get("args") or {}).get("span_id")]
        if len(evs) >= 4:
            break
        time.sleep(0.5)
    assert len(evs) >= 4
    spans = {e["args"]["span_id"] for e in evs}
    assert len(spans) >= 4  # every task got its own span
    assert all(e["args"]["parent_span_id"] == "root" for e in evs)


def test_profile_stacks_snapshots_live_worker(cluster):
    from ray_tpu.experimental.state.api import profile_stacks

    @ray_tpu.remote
    def ps_busy(sec):
        import time as _t
        _t.sleep(sec)
        return 1

    ref = ps_busy.remote(4.0)
    time.sleep(1.0)  # let it dispatch and block in sleep
    snap = profile_stacks()
    workers = [w for n in snap["nodes"] for w in n.get("workers", [])
               if "stacks" in w]
    assert workers, snap
    joined = "\n".join(w["stacks"] for w in workers)
    # the busy task's sleep frame is visible in some worker's stack
    assert "ps_busy" in joined or "_t.sleep" in joined or \
        "sleep" in joined, joined[:2000]
    busy = [w for w in workers if w.get("current_task")]
    assert busy, "no worker reported a current task"
    assert ray_tpu.get(ref, timeout=30) == 1


def test_profile_stacks_http_route(cluster):
    """The dashboard exposes the same snapshot over HTTP."""
    import json
    import urllib.request
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18271)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile/stacks",
            timeout=30) as resp:
        doc = json.loads(resp.read())
    assert "nodes" in doc


def test_flamegraph_of_busy_worker(cluster):
    """Timed sampling profile -> folded stacks: the busy function's
    frame dominates the samples (reference:
    reporter/profile_manager.py py-spy flamegraphs)."""
    import json
    import urllib.request
    from ray_tpu.dashboard.dashboard import start_dashboard

    @ray_tpu.remote
    def fg_spin(sec):
        import time as _t
        end = _t.monotonic() + sec
        acc = 0
        while _t.monotonic() < end:  # CPU-busy, stays on the stack
            acc += 1
        return acc

    ref = fg_spin.remote(6.0)
    time.sleep(1.0)  # let it dispatch
    port = start_dashboard(port=18272)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile/flamegraph"
            f"?duration_s=1.5", timeout=60) as resp:
        doc = json.loads(resp.read())
    profiles = [w for n in doc["nodes"] for w in n.get("workers", [])
                if w.get("folded")]
    assert profiles, doc
    joined = "\n".join(p["folded"] for p in profiles)
    assert "fg_spin" in joined, joined[:1500]
    # folded format: "frame;frame;... count" — flamegraph.pl-parseable
    line = next(ln for ln in joined.splitlines() if "fg_spin" in ln)
    assert line.rsplit(" ", 1)[1].isdigit()
    assert all(p["samples"] > 0 for p in profiles)
    assert ray_tpu.get(ref, timeout=60) > 0


# ---------------------------------------------------------------- spans


def test_nested_actor_task_chain_parents_under_caller(cluster):
    """Regression (ISSUE 13 satellite): a task submitted from inside an
    executing actor method must parent under the CALL's span — the
    actor worker's _root_trace used to take over at the actor boundary,
    severing every serve-replica/actor trace tree. 3-deep chain:
    driver -> actor.method -> task -> task, one trace throughout."""

    @ray_tpu.remote
    def na_leaf(x):
        return x + 1

    @ray_tpu.remote
    def na_mid(x):
        return ray_tpu.get(na_leaf.remote(x)) + 10

    @ray_tpu.remote
    class NaActor:
        def go(self, x):
            return ray_tpu.get(na_mid.remote(x)) + 100

    a = NaActor.remote()
    assert ray_tpu.get(a.go.remote(1), timeout=60) == 112
    from ray_tpu._private.worker import global_worker
    driver_trace = global_worker()._current_trace()["trace_id"]

    deadline = time.monotonic() + 15
    evs = {}
    while time.monotonic() < deadline:
        for e in ray_tpu.timeline():
            if e.get("cat") == "task" and \
                    (e.get("args") or {}).get("trace_id"):
                evs[e["name"]] = e["args"]
        if {"na_mid", "na_leaf"} <= set(evs):
            break
        time.sleep(0.5)
    assert {"na_mid", "na_leaf"} <= set(evs), sorted(evs)
    mid, leaf = evs["na_mid"], evs["na_leaf"]
    # one trace rooted at the DRIVER (not a per-actor-worker root)
    assert mid["trace_id"] == driver_trace, \
        "actor boundary severed the trace (fresh root trace)"
    assert leaf["trace_id"] == driver_trace
    # the mid task's parent is the actor CALL's span, which itself is a
    # child of the driver root — so it can't be "root"
    assert mid["parent_span_id"] != "root"
    assert leaf["parent_span_id"] == mid["span_id"]


def test_record_span_head_sampling_and_tail_keep(monkeypatch):
    """RTPU_TRACE_SAMPLE=0 head-samples everything out, but slow and
    failed spans are always kept (the tail is the point)."""
    got = []
    tracing.set_sender(lambda p: got.extend(p["spans"]) or True)
    monkeypatch.setenv("RTPU_TRACE_SAMPLE", "0.0")
    monkeypatch.setenv("RTPU_TRACE_SLOW_S", "0.5")
    tracing.refresh()
    try:
        t = time.time()
        tracing.record_span("t-fast", "s1", "fast", start_ts=t,
                            end_ts=t + 0.01)
        tracing.record_span("t-failed", "s2", "failed", start_ts=t,
                            end_ts=t + 0.01, status="error")
        tracing.record_span("t-slow", "s3", "slow", start_ts=t,
                            end_ts=t + 2.0)
        tracing.flush()
        names = {s["name"] for s in got}
        assert names == {"failed", "slow"}, names
        # and sampled() is deterministic at fractional rates
        monkeypatch.setenv("RTPU_TRACE_SAMPLE", "0.5")
        tracing.refresh()
        assert all(tracing.sampled("x%d" % i) == tracing.sampled(
            "x%d" % i) for i in range(50))
        kept = sum(tracing.sampled("y%d" % i) for i in range(400))
        assert 100 < kept < 300  # hash-uniform, not all-or-nothing
    finally:
        tracing.set_sender(None)
        # restore the conftest default (1.0) BEFORE refreshing: the
        # cached rate must not leak a partial-sampling state into the
        # rest of the suite (monkeypatch's own undo runs after this)
        monkeypatch.setenv("RTPU_TRACE_SAMPLE", "1.0")
        monkeypatch.setenv("RTPU_TRACE_SLOW_S", "1.0")
        tracing.refresh()


def test_trace_table_bounded_with_drop_counter():
    from ray_tpu._private.gcs import TraceTable
    t = TraceTable(cap=100, per_trace_cap=10)
    for i in range(50):
        for j in range(4):
            t.apply({"trace_id": f"tr{i}", "span_id": f"s{j}",
                     "name": "n", "start_ts": float(i),
                     "end_ts": float(i) + 1})
    assert t.total_spans <= 100
    assert t.dropped_spans == 200 - t.total_spans
    # newest traces survive (oldest-updated evicted first)
    assert t.get("tr49") and not t.get("tr0")
    # per-trace cap: one hot trace can't eat the table
    for j in range(50):
        t.apply({"trace_id": "hot", "span_id": f"h{j}", "name": "n",
                 "start_ts": 0.0, "end_ts": 1.0})
    assert len(t.get("hot")) == 10
    rows = {r["trace_id"]: r for r in t.summary_rows()}
    assert rows["hot"]["spans"] == 10


def test_critical_path_attribution_unit():
    """Deepest-active-span sweep: overlap never double-counts, gaps
    fall to the enclosing span, the table sums to the root's wall."""
    spans = [
        {"trace_id": "t", "span_id": "r", "name": "root",
         "phase": "transfer", "start_ts": 0.0, "end_ts": 0.100},
        {"trace_id": "t", "span_id": "q", "parent_span_id": "r",
         "name": "q", "phase": "queue", "start_ts": 0.0,
         "end_ts": 0.020},
        {"trace_id": "t", "span_id": "e", "parent_span_id": "r",
         "name": "e", "phase": "execute", "start_ts": 0.020,
         "end_ts": 0.090},
        {"trace_id": "t", "span_id": "d", "parent_span_id": "e",
         "name": "d", "phase": "deserialize", "start_ts": 0.020,
         "end_ts": 0.030},
    ]
    cp = tracing.critical_path(spans)
    ph = cp["phases"]
    assert abs(ph["queue"] - 0.020) < 1e-9
    assert abs(ph["deserialize"] - 0.010) < 1e-9
    assert abs(ph["execute"] - 0.060) < 1e-9
    assert abs(ph["transfer"] - 0.010) < 1e-9  # root residual (gap)
    assert abs(cp["attributed_s"] - cp["total_s"]) < 1e-9
    assert cp["attributed_frac"] == 1.0
    # completeness detector
    ok, _ = tracing.tree_complete(spans)
    assert ok
    ok, detail = tracing.tree_complete(spans + [
        {"trace_id": "t", "span_id": "x", "parent_span_id": "gone",
         "name": "orphan", "phase": "other", "start_ts": 0,
         "end_ts": 1}])
    assert not ok and "orphan" in detail
    # aggregate over a cohort
    agg = tracing.aggregate_critical_path([spans, spans])
    assert agg["traces"] == 2
    assert abs(agg["phases"]["execute"] - 0.120) < 1e-9


def test_serve_request_trace_end_to_end(cluster):
    """The flagship acceptance path: a request-id-tagged serve request
    yields a complete span tree whose critical path attributes >=95%
    of the client-observed latency to named phases."""
    from ray_tpu import serve
    from ray_tpu.experimental.state import api as state

    class TrApp:
        def __call__(self, x=None):
            time.sleep(0.02)
            return {"ok": True}

    h = serve.run(serve.deployment(num_replicas=1)(TrApp).bind(),
                  name="trace_e2e", route_prefix="/trace_e2e",
                  http_port=None)
    try:
        for i in range(4):  # warm replica + router + codepaths
            ray_tpu.get(h.remote({"x": 1},
                                 __rtpu_request_id__=f"tr-warm-{i}"),
                        timeout=60)
        rid = "tr-e2e-final"
        t0 = time.time()
        ray_tpu.get(h.remote({"x": 1}, __rtpu_request_id__=rid),
                    timeout=60)
        client_dt = time.time() - t0

        deadline = time.time() + 15
        spans = []
        while time.time() < deadline:
            spans = state.get_trace(rid).get("spans") or []
            if len(spans) >= 3 and tracing.tree_complete(spans)[0]:
                break
            time.sleep(0.4)
        names = {s["name"] for s in spans}
        assert any(n.startswith("serve.request:") for n in names), names
        assert any(n.startswith("replica.execute:") for n in names), \
            names
        ok, detail = tracing.tree_complete(spans)
        assert ok, detail
        cp = tracing.critical_path(spans)
        # >=95% of what the CLIENT measured lands in named phases
        assert cp["attributed_s"] >= 0.95 * client_dt, \
            (cp, client_dt)
        assert cp["phases"].get("execute", 0) > 0.015  # the sleep
        # the summary row is listable (explicit spans only: root +
        # replica.execute at minimum — no-wait assign/queue spans are
        # elided)
        rows = state.list_traces()
        assert any(r["trace_id"] == rid and r["spans"] >= 2
                   for r in rows)
    finally:
        # full serve teardown: the module-global router would otherwise
        # outlive this module's cluster and poison later test files
        serve.shutdown()


def test_trace_api_pagination(cluster):
    from ray_tpu.experimental.state import api as state
    seen = {}
    token = None
    while True:
        page = state.list_traces(page_size=2, continuation_token=token)
        for r in page:
            assert r["trace_id"] not in seen  # pages never overlap
            seen[r["trace_id"]] = r
        token = page.next_token
        if token is None:
            break
    full = state.list_traces()
    assert set(seen) == {r["trace_id"] for r in full}


def test_compiled_dag_hop_spans(cluster):
    """A >=1.6-negotiated compiled graph chains hop spans through the
    channel frames; legacy peers would simply omit them (gated)."""
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class DagTr:
        def inc(self, x):
            return x + 1

        def dbl(self, x):
            return 2 * x

    a = DagTr.bind()
    with InputNode() as inp:
        graph = a.dbl.bind(a.inc.bind(inp))
    dag = graph.compile()
    try:
        assert dag._compiled and dag._trace_peers
        assert dag.execute(5) == 12
        from ray_tpu.experimental.state import api as state
        from ray_tpu._private.worker import global_worker
        trace_id = global_worker()._current_trace()["trace_id"]
        deadline = time.time() + 15
        hops = []
        while time.time() < deadline:
            spans = state.get_trace(trace_id).get("spans") or []
            hops = [s for s in spans if s.get("kind") == "dag.hop"]
            if len(hops) >= 2:
                break
            time.sleep(0.4)
        assert len(hops) >= 2, spans
        by_name = {s["name"]: s for s in hops}
        root = next(s for s in spans if s.get("kind") == "dag.execute")
        assert by_name["dag.stage:inc"]["parent_span_id"] == \
            root["span_id"]
        assert by_name["dag.stage:dbl"]["parent_span_id"] == \
            by_name["dag.stage:inc"]["span_id"]
    finally:
        dag.teardown()


def test_task_phase_synthesis_from_state_engine(cluster):
    """get_trace synthesizes queue/schedule/dispatch/execute phase
    spans for plain tasks from the task table's per-state stamps — no
    span instrumentation on the task hot path."""
    from ray_tpu.experimental.state import api as state
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    def synth_work(arr, ms):
        time.sleep(ms / 1e3)
        return ms

    # a plasma arg disqualifies the leased fast lane, so the task rides
    # the raylet queue and picks up queue/schedule/dispatch stamps
    big = ray_tpu.put(np.zeros(200_000))
    assert ray_tpu.get(synth_work.remote(big, 30), timeout=60) == 30
    trace_id = global_worker()._current_trace()["trace_id"]
    deadline = time.time() + 15
    task_spans = []
    while time.time() < deadline:
        spans = state.get_trace(trace_id).get("spans") or []
        task_spans = [s for s in spans if s.get("kind") == "task"
                      and s["name"].startswith("synth_work")]
        if any(s["phase"] == "execute" for s in task_spans):
            break
        time.sleep(0.5)
    phases = {s["phase"] for s in task_spans}
    assert "execute" in phases, task_spans
    assert "queue" in phases or "schedule" in phases, task_spans
    execute = next(s for s in task_spans if s["phase"] == "execute")
    assert execute["end_ts"] - execute["start_ts"] >= 0.025


def test_dashboard_trace_routes(cluster):
    import json
    import urllib.request
    from ray_tpu.dashboard.dashboard import start_dashboard

    @ray_tpu.remote
    def dtr_noop():
        return 1

    assert ray_tpu.get(dtr_noop.remote(), timeout=60) == 1
    time.sleep(1.2)  # task events flush
    port = start_dashboard(port=18273)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/traces?limit=5",
            timeout=30) as resp:
        doc = json.loads(resp.read())
    assert doc["traces"], doc
    tid = doc["traces"][0]["trace_id"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/trace/{tid}",
            timeout=30) as resp:
        one = json.loads(resp.read())
    assert one["spans"]
    assert "critical_path" in one and "complete" in one
    # timeline route surfaces the ring drop counter
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/timeline",
            timeout=30) as resp:
        tl = json.loads(resp.read())
    assert "dropped" in tl


def test_chrome_export_merges_device_spans():
    """Trace spans + tpu_profiler XLA rows concatenate onto one
    wall-clock axis (the `ray-tpu trace show --chrome` document)."""
    from ray_tpu.util.tpu_profiler import _XLA_PID_BASE
    now = time.time()
    spans = [{"trace_id": "t", "span_id": "r", "name": "root",
              "phase": "execute", "start_ts": now, "end_ts": now + 1}]
    device = [
        {"name": "process_name", "ph": "M", "ts": 0,
         "pid": _XLA_PID_BASE + 7, "args": {"name": "xla host p1"}},
        {"name": "fusion.1", "ph": "X", "ts": (now + 0.5) * 1e6,
         "dur": 1000.0, "pid": _XLA_PID_BASE + 7, "tid": 0},
        {"name": "far-away", "ph": "X", "ts": (now + 3600) * 1e6,
         "dur": 5.0, "pid": _XLA_PID_BASE + 7, "tid": 0},
        {"name": "not-xla-row", "ph": "X", "ts": (now + 0.5) * 1e6,
         "dur": 5.0, "pid": 1234, "tid": 0},
    ]
    doc = tracing.export_chrome(spans, device_events=device)
    names = [e["name"] for e in doc]
    assert "root" in names and "fusion.1" in names
    assert "process_name" in names          # XLA lane labels ride along
    assert "far-away" not in names          # outside the trace window
    assert "not-xla-row" not in names       # framework rows excluded
    root_ev = next(e for e in doc if e["name"] == "root")
    fusion = next(e for e in doc if e["name"] == "fusion.1")
    # one time axis: both in wall-clock microseconds
    assert root_ev["ts"] <= fusion["ts"] <= root_ev["ts"] + 1e6


def test_timeline_drop_counter_and_flusher_stop():
    """Satellite: the timeline ring reports what it trims, and the
    flusher thread dies on stop_flusher (one thread leaked per
    init/shutdown cycle before)."""
    import threading
    from ray_tpu.util import timeline

    base = timeline.dropped_count()
    for i in range(timeline._MAX_EVENTS + 50):
        timeline.record("spam", "X", float(i))
    assert timeline.dropped_count() >= base + 50
    # the dump carries the loss marker (per-process metadata event)
    evs = timeline.timeline_dump()
    assert timeline.dump_dropped_total(evs) >= base + 50

    def flusher_threads():
        return [t for t in threading.enumerate()
                if t.name == "rtpu-timeline-flush" and t.is_alive()]

    # record_task is the path that lazily starts the flusher
    timeline.record_task("flusher-probe", time.time(),
                         time.time() + 1e-4)
    assert flusher_threads()
    timeline.stop_flusher()
    deadline = time.time() + 5
    while flusher_threads() and time.time() < deadline:
        time.sleep(0.2)
    assert not flusher_threads(), "flusher thread survived stop"
    # a later record_task starts a fresh one (reconnect works)
    timeline.record_task("again", time.time(), time.time() + 1e-4)
    assert flusher_threads()
    timeline.stop_flusher()
