"""Streaming data-plane executor tests (tier-1, CPU-only).

Covers the ISSUE-1 acceptance surface: time-to-first-batch precedes a
slow tail block, the in-flight task/byte budgets are respected (asserted
via the per-operator stats in Dataset.stats()), streaming and bulk
produce identical rows for map/filter/repartition chains under both
RTPU_DATA_STREAMING settings, pipeline windows yield mid-window, and the
bulk path's prefetch thread no longer leaks on iterator abandonment.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


pytestmark = pytest.mark.usefixtures("ray_start_shared")


@pytest.fixture(scope="module", autouse=True)
def _warm_worker_pool(ray_start_shared):
    """Spawn the worker pool once so the timing asserts below measure the
    pipeline, not cold worker startup (~1s/proc on the CI box)."""
    rd.range(8, parallelism=8).map(lambda x: x).take_all()


@pytest.fixture(autouse=True)
def _streaming_on(monkeypatch):
    monkeypatch.setenv("RTPU_DATA_STREAMING", "1")


def _slow_on(value, seconds):
    def fn(batch):
        if int(np.max(batch)) == value:
            time.sleep(seconds)
        return batch
    return fn


def test_first_batch_precedes_slow_tail_block():
    # 8 single-row blocks; the LAST block's map sleeps 2s.  Streaming must
    # yield the first batch after the first block chain, not the last.
    ds = rd.range(8, parallelism=8).map_batches(
        _slow_on(7, 2.0), batch_format="numpy")
    t0 = time.perf_counter()
    it = ds.iter_batches(batch_size=1, batch_format="numpy")
    first = next(it)
    t_first = time.perf_counter() - t0
    rest = list(it)
    t_total = time.perf_counter() - t0
    assert first.tolist() == [0]
    assert len(rest) == 7
    assert t_total >= 1.8  # the tail block really did sleep
    assert t_first < 1.2, f"first batch took {t_first:.2f}s (bulk-like)"


def test_inflight_task_budget_respected(monkeypatch):
    monkeypatch.setenv("RTPU_DATA_MAX_INFLIGHT_TASKS", "2")
    ds = rd.range(64, parallelism=8).map_batches(
        lambda b: b, batch_format="numpy")
    rows = [v for b in ds.iter_batches(batch_size=8, batch_format="numpy")
            for v in b.tolist()]
    assert sorted(rows) == list(range(64))
    row = [r for r in ds._plan.stats.to_dict()
           if "map_batches" in r["stage"]][-1]
    assert row["streaming"] is True
    assert 1 <= row["peak_inflight_tasks"] <= 2, row
    assert row["queue_depth_max"] <= 2, row
    assert row["tasks"] == 8 and row["rows_out"] == 64


def test_buffered_bytes_budget_respected(monkeypatch):
    budget = 64 * 1024
    monkeypatch.setenv("RTPU_DATA_MAX_BUFFERED_BYTES", str(budget))
    # 8 blocks x 4 rows x 8 KiB/row = 32 KiB per block -> at most two
    # blocks fit in flight under a 64 KiB budget
    ds = rd.range_tensor(32, shape=(1024,), parallelism=8).map_batches(
        lambda b: b, batch_format="numpy")
    n = sum(1 for _ in ds.iter_batches(batch_size=4, batch_format="numpy"))
    assert n == 8
    row = [r for r in ds._plan.stats.to_dict()
           if "map_batches" in r["stage"]][-1]
    assert row["peak_buffered_bytes"] <= budget, row
    assert row["peak_inflight_tasks"] <= 2, row
    assert row["backpressure_wait_s"] >= 0


@pytest.mark.parametrize("mode", ["1", "0"], ids=["streaming", "bulk"])
def test_streaming_bulk_identical_rows(monkeypatch, mode):
    monkeypatch.setenv("RTPU_DATA_STREAMING", mode)

    def build():
        return (rd.range(50, parallelism=5)
                .map(lambda x: x + 1)
                .filter(lambda x: x % 2 == 0)
                .repartition(3)
                .map_batches(lambda b: b * 2, batch_format="numpy"))

    via_iter = [v for b in build().iter_batches(batch_size=7,
                                                batch_format="numpy")
                for v in b.tolist()]
    via_rows = list(build().iter_rows())
    via_bulk = build().take_all()  # take_all always bulk-materializes
    expected = [(x + 1) * 2 for x in range(50) if (x + 1) % 2 == 0]
    assert via_iter == expected
    assert via_rows == expected
    assert via_bulk == expected


def test_partial_consumption_then_bulk_reuse():
    # take() abandons the stream early; the plan stays lazy and a later
    # bulk consumer still sees every row exactly once
    ds = rd.range(32, parallelism=8).map(lambda x: x * 2)
    assert ds.take(3) == [0, 2, 4]
    assert ds.count() == 32
    assert sorted(ds.take_all()) == [x * 2 for x in range(32)]


def test_pipeline_window_yields_mid_window():
    # one window of 8 blocks whose tail block sleeps: the first batch
    # must arrive while the window is still executing (the pre-streaming
    # executor fully executed each window before yielding)
    pipe = rd.range(8, parallelism=8).window(blocks_per_window=8) \
        .map_batches(_slow_on(7, 1.5), batch_format="numpy")
    t0 = time.perf_counter()
    it = pipe.iter_batches(batch_size=1, batch_format="numpy")
    first = next(it)
    t_first = time.perf_counter() - t0
    rest = list(it)
    assert first.tolist() == [0]
    assert len(rest) == 7
    assert t_first < 1.0, f"window bulk-executed ({t_first:.2f}s)"


def test_streaming_split_carries_stages():
    ds = rd.range(40, parallelism=8).map(lambda x: x + 100)
    shards = ds.streaming_split(4)
    assert len(shards) == 4
    # every shard still has the un-executed map chain
    assert all(s._plan._stages for s in shards)
    vals = sorted(v for s in shards for v in s.iter_rows())
    assert vals == [x + 100 for x in range(40)]
    # an executed or all-to-all plan falls back to the row-equal split
    eq = rd.range(40, parallelism=8).materialize().streaming_split(4)
    assert [s.count() for s in eq] == [10, 10, 10, 10]


def test_all_to_all_barrier_then_streaming_resumes():
    ds = (rd.range(24, parallelism=6)
          .map(lambda x: x + 1)
          .random_shuffle(seed=11)
          .map_batches(lambda b: b, batch_format="numpy"))
    vals = sorted(v for b in ds.iter_batches(batch_size=5,
                                             batch_format="numpy")
                  for v in b.tolist())
    assert vals == list(range(1, 25))
    names = [r["stage"] for r in ds._plan.stats.to_dict()]
    assert "random_shuffle" in names


def test_prefetch_thread_joined_on_abandon(monkeypatch):
    monkeypatch.setenv("RTPU_DATA_STREAMING", "0")
    ds = rd.range(64, parallelism=8)
    it = ds.iter_batches(batch_size=8, batch_format="numpy",
                         prefetch_blocks=3)
    next(it)
    it.close()  # abandon mid-stream; close must reap the prefetch thread
    deadline = time.time() + 5
    while time.time() < deadline and any(
            t.name == "rtpu-data-prefetch" for t in threading.enumerate()):
        time.sleep(0.05)
    leaked = [t.name for t in threading.enumerate()
              if t.name == "rtpu-data-prefetch"]
    assert not leaked, leaked
