"""Native RPC frame pump (src/rpccore/) + direct-execution lane.

Covers the PR-15 perf plane (docs/WIRE_PROTOCOL.md "Implementations"):
the pump itself (framing, batching, close semantics), selection and
fallback rules (RTPU_NATIVE_RPC=0, library load failure), and the
direct lane end-to-end — correctness of results/errors/plasma returns,
worker-death failover, and idle lease release.  Byte-level conformance
vectors live in test_wire_conformance.py; chaos frame faults against
the pump live in test_chaos.py.
"""

import os
import socket
import tempfile
import threading
import time

import msgpack
import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import protocol, rpccore


pytestmark = pytest.mark.skipif(
    rpccore._lib() is None,
    reason="native rpc library unavailable on this host")


# ------------------------------------------------------------- pump units


def _mk_pair():
    srv, cli = rpccore.Pump(), rpccore.Pump()
    path = tempfile.mktemp(suffix=".sock")
    srv.listen(path)
    cid = cli.dial(path)
    return srv, cli, cid, path


def _close(*pumps):
    for p in pumps:
        p.shutdown()
        p.destroy()


def _first_frames(pump, n=1, timeout_s=5):
    out = []
    deadline = time.monotonic() + timeout_s
    while len(out) < n and time.monotonic() < deadline:
        for cid, kind, body in pump.next_batch(200) or []:
            if kind == rpccore.KIND_FRAME:
                out.append((cid, body))
    return out


def test_pump_echo_roundtrip():
    srv, cli, cid, path = _mk_pair()
    try:
        body = msgpack.packb([0, 1, "ping", {}], use_bin_type=True)
        assert cli.send(cid, body)
        (scid, got), = _first_frames(srv)
        assert got == body
        assert srv.send(scid, got)
        (_, back), = _first_frames(cli)
        assert back == body
    finally:
        _close(srv, cli)
        os.unlink(path)


def test_pump_delivers_pipelined_frames_in_order_and_batched():
    """Many frames written back-to-back arrive in order, and the pump
    coalesces them: the consumer sees multi-frame batches and the
    socket was drained with fewer reads than frames."""
    srv, cli, cid, path = _mk_pair()
    try:
        n = 200
        for i in range(n):
            assert cli.send(cid, msgpack.packb(i))
        got = _first_frames(srv, n)
        assert [msgpack.unpackb(b) for _, b in got] == list(range(n))
        stats = srv.stats()
        assert stats["frames_in"] == n
        # coalescing proof: the reader pulled multiple frames per recv
        assert stats["read_calls"] < n
    finally:
        _close(srv, cli)
        os.unlink(path)


def test_pump_close_event_and_dead_send():
    srv, cli, cid, path = _mk_pair()
    try:
        assert cli.send(cid, b"x")
        _first_frames(srv, 1)
        cli.close_conn(cid)
        deadline = time.monotonic() + 5
        closed = False
        while time.monotonic() < deadline and not closed:
            for _, kind, _b in srv.next_batch(200) or []:
                closed = closed or kind == rpccore.KIND_CLOSED
        assert closed
        assert cli.send(cid, b"y") is False  # poisoned, not crashed
    finally:
        _close(srv, cli)
        os.unlink(path)


def test_pump_wake_bounces_next_batch():
    p = rpccore.Pump()
    try:
        got = []

        def wait():
            got.append(p.next_batch(5000))
        t = threading.Thread(target=wait, daemon=True)
        t.start()
        time.sleep(0.1)
        p.wake()
        t.join(2)
        assert not t.is_alive()
        assert got and got[0] and got[0][0][1] == rpccore.KIND_WAKE
    finally:
        _close(p)


def test_env_gate():
    old = os.environ.get("RTPU_NATIVE_RPC")
    try:
        os.environ["RTPU_NATIVE_RPC"] = "0"
        assert not rpccore.env_enabled()
        assert not rpccore.available()
        os.environ["RTPU_NATIVE_RPC"] = "1"
        assert rpccore.env_enabled()
        os.environ.pop("RTPU_NATIVE_RPC")
        assert rpccore.env_enabled()  # default ON
    finally:
        if old is None:
            os.environ.pop("RTPU_NATIVE_RPC", None)
        else:
            os.environ["RTPU_NATIVE_RPC"] = old


# ------------------------------------------------- selection and fallback


def test_forced_fallback_env(monkeypatch):
    """RTPU_NATIVE_RPC=0 forces the pure-Python path end-to-end: no
    direct client in the driver, no direct lane in the workers, tasks
    still run (through the asyncio lease pool)."""
    monkeypatch.setenv("RTPU_NATIVE_RPC", "0")
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        from ray_tpu._private import worker as wmod
        assert wmod._global_worker._direct_client is None
        assert wmod._global_worker.direct_address == ""

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert [ray_tpu.get(f.remote(i), timeout=60) for i in range(5)] \
            == [1, 2, 3, 4, 5]
    finally:
        ray_tpu.shutdown()


def test_graceful_fallback_when_library_absent(monkeypatch):
    """A failed library build/load must leave the runtime fully
    functional on the asyncio path (the ISSUE's hard fallback rule)."""
    monkeypatch.setattr(rpccore, "_LIB", None)
    monkeypatch.setattr(rpccore, "_LIB_FAILED", True)
    assert not rpccore.available()
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        from ray_tpu._private import worker as wmod
        assert wmod._global_worker._direct_client is None

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get(f.remote(21), timeout=60) == 42
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------- direct lane e2e


@pytest.fixture()
def native_cluster(monkeypatch):
    monkeypatch.setenv("RTPU_NATIVE_RPC", "1")
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _direct_client():
    from ray_tpu._private import worker as wmod
    return wmod._global_worker._direct_client


def test_direct_lane_carries_unary_tasks(native_cluster):
    @ray_tpu.remote
    def f(x, y=0):
        return x + y

    # warm the lease, then verify results and that the native lane —
    # not the asyncio pool — carried them
    assert ray_tpu.get(f.remote(1), timeout=60) == 1
    before = _direct_client().submitted
    vals = [ray_tpu.get(f.remote(i, y=i), timeout=60) for i in range(20)]
    assert vals == [2 * i for i in range(20)]
    assert _direct_client().submitted >= before + 20


def test_direct_lane_app_errors_and_retry_exceptions(native_cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("direct-lane boom")

    with pytest.raises(Exception) as ei:
        ray_tpu.get(boom.remote(), timeout=60)
    assert "direct-lane boom" in str(ei.value)

    # retry_exceptions rides the same reply envelope
    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def flaky(path):
        import os as _os
        if not _os.path.exists(path):
            with open(path, "w") as fh:
                fh.write("1")
            raise RuntimeError("first attempt fails")
        return "ok"

    flag = tempfile.mktemp()
    try:
        assert ray_tpu.get(flaky.remote(flag), timeout=60) == "ok"
    finally:
        if os.path.exists(flag):
            os.unlink(flag)


def test_direct_lane_plasma_returns_zero_copy(native_cluster):
    """Large returns from a direct-lane task ride plasma (the reply
    carries a descriptor, not bytes) and come back intact."""
    @ray_tpu.remote
    def big():
        return np.arange(500_000, dtype=np.int64)  # 4 MB > inline cap

    out = ray_tpu.get(big.remote(), timeout=60)
    assert out.shape == (500_000,) and out[123456] == 123456


def test_direct_lane_worker_death_fails_over(native_cluster):
    """SIGKILL the executing worker mid-direct-task: the severed pump
    connection resubmits the in-flight task through the batched raylet
    path (at-least-once, same contract as the asyncio lease lane)."""
    @ray_tpu.remote(max_retries=2)
    def die_once(path):
        import os as _os
        if not _os.path.exists(path):
            with open(path, "w") as fh:
                fh.write("1")
            _os._exit(1)  # hard death, no cleanup
        return "survived"

    flag = tempfile.mktemp()
    try:
        assert ray_tpu.get(die_once.remote(flag), timeout=90) == "survived"
    finally:
        if os.path.exists(flag):
            os.unlink(flag)


def test_direct_lease_idle_release(native_cluster):
    """An idle direct lease releases within the idle window so it stops
    pinning node capacity (same policy as the asyncio pool)."""
    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=60) == 1
    dc = _direct_client()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all(not pool for pool in dc.pools.values()):
            break
        time.sleep(0.25)
    assert all(not pool for pool in dc.pools.values()), dc.pools


def test_direct_server_answers_hello_and_ping(native_cluster):
    """The direct socket speaks the standard wire protocol: __hello__
    negotiation and ping work against it from a raw client pump."""
    from ray_tpu._private import schema
    from ray_tpu._private import worker as wmod

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=60) == 1
    # find the worker's direct socket from the session dir instead of
    # relying on pool state (the lease may have idled away)
    session = wmod._global_worker.session_dir
    socks = [f for f in os.listdir(session) if f.endswith(".direct.sock")]
    assert socks, "no direct sockets registered"
    cli = rpccore.Pump()
    try:
        cid = cli.dial(os.path.join(session, socks[0]))
        cli.send(cid, msgpack.packb(
            [protocol.REQUEST, 1, "__hello__", schema.hello_payload()],
            use_bin_type=True))
        cli.send(cid, msgpack.packb(
            [protocol.REQUEST, 2, "ping", {}], use_bin_type=True))
        replies = {}
        deadline = time.monotonic() + 10
        while len(replies) < 2 and time.monotonic() < deadline:
            for _cid, kind, body in cli.next_batch(200) or []:
                if kind != rpccore.KIND_FRAME:
                    continue
                mtype, seq, method, payload = msgpack.unpackb(
                    body, raw=False)
                replies[seq] = (mtype, payload)
        assert replies[1][0] == protocol.REPLY
        assert replies[1][1]["protocol_version"][0] == \
            schema.PROTOCOL_VERSION[0]
        assert replies[2][0] == protocol.REPLY
        assert replies[2][1]["mode"] == "worker"
    finally:
        _close(cli)
