"""Serve data-plane tests: load-aware routing (power-of-two-choices),
adaptive micro-batching, and replica backpressure (bounded ingress
queue → retriable shed → HTTP 503). Tier-1, CPU-only."""

import json
import logging
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.exceptions import (BatchSubmitTimeoutError,
                                      ReplicaOverloadedError)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------- replica backpressure

class _SlowCallable:
    def __init__(self, delay):
        self.delay = delay

    def __call__(self, x):
        time.sleep(self.delay)
        return x


def _make_replica(cls, mcq, max_queued, *init_args):
    import cloudpickle

    from ray_tpu.serve._private.replica import ReplicaActor
    return ReplicaActor("TestDep", cloudpickle.dumps(cls), init_args, {},
                        max_concurrent_queries=mcq,
                        max_queued_requests=max_queued)


def test_replica_sheds_past_bounded_queue():
    # 1 execution slot + 1 waiting-room slot: of 6 concurrent requests,
    # exactly 2 are admitted and 4 shed with a retriable error
    r = _make_replica(_SlowCallable, 1, 1, 0.3)
    results, errors = [], []
    barrier = threading.Barrier(6)

    def call(i):
        barrier.wait()
        try:
            results.append(r.handle_request("__call__", (i,), {}))
        except ReplicaOverloadedError as e:
            errors.append(e)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 2
    assert len(errors) == 4
    assert "retriable" in str(errors[0])
    m = r.get_metrics()
    assert m["total_shed"] == 4
    assert m["queue_len"] == 0  # fully drained
    assert m["ewma_service_time_s"] > 0


def test_replica_load_telemetry():
    r = _make_replica(_SlowCallable, 4, 4, 0.01)
    for i in range(3):
        r.handle_request("__call__", (i,), {})
    load = r.get_load()
    assert load["queue_len"] == 0
    assert load["ewma_s"] >= 0.01
    assert abs(load["ts"] - time.time()) < 5.0
    assert load["shed"] == 0


# ------------------------------------------------------- replica selection

class _FakeReplica:
    def __init__(self, id_hex):
        self._id_hex = id_hex


def _replica_set(policy, mcq=100, n=2):
    from ray_tpu.serve._private.router import ReplicaSet
    rs = ReplicaSet("dep", max_concurrent_queries=mcq)
    reps = [_FakeReplica(chr(ord("a") + i) * 8) for i in range(n)]
    rs.update_replicas(reps, routing_policy=policy)
    return rs, reps


def test_p2c_prefers_reported_less_loaded():
    rs, (a, b) = _replica_set("p2c")
    now = time.time()
    rs.record_report(a._id_hex, queue_len=50, ewma_s=0.1, ts=now)
    rs.record_report(b._id_hex, queue_len=0, ewma_s=0.1, ts=now)
    picks = {a._id_hex: 0, b._id_hex: 0}
    for _ in range(40):
        r = rs.assign(timeout=1.0)
        picks[r._id_hex] += 1
        rs.release(r)
    # with 2 replicas both are always sampled; the lower queue wins
    assert picks[b._id_hex] == 40


def test_stale_report_falls_back_to_local_counts():
    rs, (a, b) = _replica_set("p2c")
    # a's report is ancient and must be ignored, despite the huge queue
    rs.record_report(a._id_hex, queue_len=1000, ewma_s=0.1,
                     ts=time.time() - 3600)
    with rs._cv:  # 5 of our own requests outstanding on b
        rs._in_flight[b._id_hex] = 5
    for _ in range(10):
        r = rs.assign(timeout=1.0)
        assert r._id_hex == a._id_hex
        rs.release(r)


def test_round_robin_policy_alternates():
    rs, (a, b) = _replica_set("round_robin")
    order = []
    for _ in range(4):
        r = rs.assign(timeout=1.0)
        order.append(r._id_hex)
        rs.release(r)
    assert order == [a._id_hex, b._id_hex, a._id_hex, b._id_hex]


def test_assign_timeout_message_reflects_racing_update():
    # regression: update_replicas racing the wait loop must not leave a
    # stale replica count in the TimeoutError message
    rs, (a, b) = _replica_set("round_robin", mcq=1)
    rs.assign(timeout=1.0)  # saturate a
    rs.assign(timeout=1.0)  # saturate b

    def shrink():
        time.sleep(0.3)
        rs.update_replicas([a])  # b disappears mid-wait

    t = threading.Thread(target=shrink)
    t.start()
    with pytest.raises(TimeoutError) as ei:
        rs.assign(timeout=0.9)
    t.join()
    msg = str(ei.value)
    assert "(1 replicas" in msg
    assert "2 replicas" not in msg


class _FakeRemoteMethod:
    def remote(self, *a, **k):
        raise TimeoutError("controller busy")


class _FakeController:
    def __init__(self):
        self.get_route_table = _FakeRemoteMethod()
        self.listen_for_change = _FakeRemoteMethod()


def test_router_seed_failure_is_logged_not_swallowed(caplog):
    from ray_tpu.serve._private.router import Router
    with caplog.at_level(logging.WARNING, logger="ray_tpu.serve.router"):
        router = Router(_FakeController())
        router.stop()
    assert any("seed" in rec.getMessage()
               for rec in caplog.records), caplog.records


# ------------------------------------------------------------- batching

def test_singleton_pad_flush_shape():
    sizes = []

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01,
                 pad_to_bucket=True, min_pad_bucket=4)
    def handler(items):
        sizes.append(len(items))
        return items

    # a singleton flush must also pad — an unpadded stray shape would
    # mean a fresh JAX compile mid-traffic
    assert handler(7) == 7
    assert sizes == [4]


def test_batch_fn_error_unblocks_all_waiters():
    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    def handler(items):
        raise ValueError("boom")

    errs = []

    def call(i):
        try:
            handler(i)
        except ValueError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == ["boom"] * 3


def test_late_enqueue_rearms_flusher():
    release = threading.Event()
    sizes = []

    @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.01)
    def handler(items):
        sizes.append(len(items))
        release.wait(5.0)
        return items

    results = []

    def call(i):
        results.append(handler(i))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # first flush is now blocked inside the batch fn
    release.set()
    for t in threads:
        t.join(timeout=10.0)
    assert sorted(results) == [0, 1, 2]
    assert sum(sizes) == 3
    assert max(sizes) <= 2  # cap respected across re-armed flushes


def test_submit_timeout_surfaces_clear_error():
    @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.01,
                 submit_timeout_s=0.2)
    def handler(items):
        time.sleep(2.0)  # wedged batch fn
        return items

    t0 = time.monotonic()
    with pytest.raises(BatchSubmitTimeoutError) as ei:
        handler(1)
    assert time.monotonic() - t0 < 1.5  # did not wait out the batch fn
    assert "submit_timeout_s" in str(ei.value)


def test_adaptive_batching_flushes_idle_queue_immediately():
    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.3,
                 adaptive=True)
    def fast(items):
        return items

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.3,
                 adaptive=False)
    def fixed(items):
        return items

    t0 = time.perf_counter()
    assert fast(1) == 1
    adaptive_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    assert fixed(1) == 1
    fixed_dt = time.perf_counter() - t0
    assert adaptive_dt < 0.15, adaptive_dt  # no idle wait window
    assert fixed_dt >= 0.25, fixed_dt  # fixed mode pays the full window


def test_prewarm_compiles_every_bucket():
    sizes = []

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01,
                 pad_to_bucket=True)
    def handler(items):
        sizes.append(len(items))
        return items

    handler.prewarm(0)
    assert sizes == [1, 2, 4, 8]


def test_method_prewarm_uses_instance():
    class Scorer:
        def __init__(self, scale):
            self.scale = scale

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.01,
                     pad_to_bucket=True)
        def score(self, items):
            return [i * self.scale for i in items]

    s = Scorer(10)
    s.score.prewarm(s, 1)  # must not raise; compiles buckets 1,2,4
    assert s.score(2) == 20


# ------------------------------------------------------- cluster tests

@pytest.fixture(scope="module")
def serve_cluster():
    # env must be set BEFORE init so worker processes (proxy, replicas)
    # inherit it
    os.environ["RTPU_SERVE_PROXY_ASSIGN_TIMEOUT_S"] = "0.4"
    ctx = ray_tpu.init(num_cpus=8, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()
    os.environ.pop("RTPU_SERVE_PROXY_ASSIGN_TIMEOUT_S", None)


def test_saturated_deployment_sheds_503(serve_cluster):
    @serve.deployment(num_replicas=1, max_concurrent_queries=1,
                      max_queued_requests=0)
    class OneSlot:
        def __call__(self, payload=None):
            time.sleep(1.2)
            return {"ok": True}

    serve.run(OneSlot.bind(), name="shed", route_prefix="/oneslot",
              http_port=8124)
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    outcomes = []
    lock = threading.Lock()

    def get():
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/oneslot", timeout=30)
            body = json.loads(resp.read())
            with lock:
                outcomes.append((resp.status, body))
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            with lock:
                outcomes.append((e.code, body))

    threads = [threading.Thread(target=get) for _ in range(3)]
    for t in threads:
        t.start()
        time.sleep(0.05)
    for t in threads:
        t.join()
    codes = [c for c, _ in outcomes]
    assert 200 in codes, outcomes  # the admitted request completed
    shed = [(c, b) for c, b in outcomes if c == 503]
    assert shed, outcomes  # saturation shed instead of queueing
    assert all(b.get("retryable") for _, b in shed), outcomes


def test_replica_shed_is_retriable_actor_error(serve_cluster):
    from ray_tpu.actor import get_actor_by_id
    from ray_tpu.serve.controller import CONTROLLER_NAME
    from ray_tpu.serve._private.router import is_overload_error

    @serve.deployment(num_replicas=1, max_concurrent_queries=1,
                      max_queued_requests=0, name="ShedDirect")
    class OneSlot2:
        def __call__(self, x):
            time.sleep(0.8)
            return x

    serve.run(OneSlot2.bind(), name="shed2", http_port=None)
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    _, table = ray_tpu.get(controller.get_route_table.remote())
    replica = get_actor_by_id(table["ShedDirect"]["replicas"][0])
    # bypass the router's own in-flight cap: hit the replica directly,
    # like a second router that hasn't seen this load yet would
    refs = [replica.handle_request.remote("__call__", (i,), {})
            for i in range(4)]
    results, errors = [], []
    for ref in refs:
        try:
            results.append(ray_tpu.get(ref, timeout=30.0))
        except Exception as e:  # noqa: BLE001 — asserting on type below
            errors.append(e)
    assert results, "the admitted request must complete"
    assert errors, "overflow must be shed"
    assert all(is_overload_error(e) for e in errors), errors


def test_router_receives_load_reports_via_long_poll(serve_cluster):
    from ray_tpu.serve import handle as handle_mod

    @serve.deployment(num_replicas=2, name="LoadRep")
    class Echo2:
        def __call__(self, x):
            return x

    h = serve.run(Echo2.bind(), name="loadrep", http_port=None)
    assert ray_tpu.get(h.remote(7), timeout=30.0) == 7
    router = handle_mod._router
    assert router is not None
    deadline = time.time() + 15.0
    reports = {}
    while time.time() < deadline:
        rs = router._sets.get("LoadRep")
        if rs is not None:
            with rs._cv:
                reports = dict(rs._reports)
            if reports:
                break
        time.sleep(0.2)
    assert reports, "controller never published replica_load"
    sample = next(iter(reports.values()))
    assert "queue_len" in sample and "ts" in sample


def test_bench_serve_smoke():
    env = dict(os.environ, _BENCH_SERVE="1", JAX_PLATFORMS="cpu",
               BENCH_SERVE_DURATION="0.3", BENCH_SERVE_CLIENTS="3",
               BENCH_SERVE_SERVICE_MS="2", BENCH_SERVE_SKEW="5")
    env.pop("LIBTPU_INIT_ARGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        stdout=subprocess.PIPE, text=True, timeout=240, env=env,
        cwd=REPO_ROOT)
    row = None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            row = json.loads(line)
            break
    assert row is not None, proc.stdout
    assert row.get("metric") == "serve_dataplane", row
    for key in ("route_round_robin_rps", "route_p2c_rps",
                "route_p2c_p50_ms", "route_p2c_p99_ms", "http_rps",
                "http_p50_ms", "http_p99_ms", "batch_fixed_idle_p50_ms",
                "batch_adaptive_idle_p50_ms", "batch_fixed_rps",
                "batch_adaptive_rps"):
        assert key in row, (key, row)
