"""Tree broadcast mechanism (reference: push_manager.cc's role).

Deterministic check of the fan-out protocol itself — a source over its
outbound-stream cap answers "busy", surplus readers retry against the
refreshed directory, and completed pulls register new sources — using
tiny thresholds so the behavior is forced regardless of timing.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import cluster_utils


@pytest.fixture
def tree_cluster(monkeypatch):
    # every object is "large" and every node serves ONE stream at a
    # time: any 3-reader broadcast MUST exercise busy -> retry -> new
    # sources to complete
    monkeypatch.setenv("RTPU_OBJECT_SERVE_TREE_MIN_BYTES", "1024")
    monkeypatch.setenv("RTPU_OBJECT_SERVE_CONCURRENCY", "1")
    c = cluster_utils.Cluster(head_node_args={
        "num_cpus": 2, "object_store_memory": 256 * 1024 * 1024})
    c.add_nodes(4, num_cpus=1, object_store_memory=128 * 1024 * 1024)
    c.connect()
    c.wait_for_nodes(timeout=120)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_broadcast_completes_through_busy_sources(tree_cluster):
    big = np.arange(8 * 1024 * 1024, dtype=np.uint8)  # 8 MiB
    ref = ray_tpu.put(big)

    @ray_tpu.remote
    def readback(x):
        return int(x[:16].sum()), len(x)

    refs = [readback.options(scheduling_strategy="SPREAD").remote(ref)
            for _ in range(4)]
    results = ray_tpu.get(refs, timeout=300)
    want = (int(big[:16].sum()), len(big))
    assert all(tuple(r) == want for r in results)

    # the object's directory should list multiple sources now — every
    # completed pull registered its node as a copy (the property that
    # makes the fan-out a TREE rather than head-serialized)
    from ray_tpu._private import worker as wmod
    w = wmod._global_worker
    deadline = time.time() + 30
    n_locs = 0
    while time.time() < deadline:
        r = w.call_sync(w.gcs, "get_object_locations",
                        {"object_id": ref.id().hex()})
        n_locs = len(r["locations"])
        if n_locs >= 3:
            break
        time.sleep(0.5)
    assert n_locs >= 3, f"only {n_locs} registered copies"
