"""Shutdown hygiene of the RPC plane (reference: the reference's
core_worker/raylet destructors join their io_service threads —
src/ray/common/asio/ — so no pending handler outlives its loop).

These are the regression tests for the round-4 verdict item "every
long-lived process sprays 'Task was destroyed but it is pending!' on
shutdown": Connection.close() must cancel its read loop, aclose() must
wait for the unwind, EventLoopThread.stop() must drain every pending
task before closing the loop, and single-flight dialing must never
leak a raced Connection.
"""

import asyncio
import gc

import pytest

from ray_tpu._private import protocol


async def _echo_handler(method, payload, conn):
    return payload


@pytest.fixture
def io():
    t = protocol.EventLoopThread(name="test-io")
    yield t
    t.stop()


def test_connection_close_cancels_read_loop(io):
    async def scenario():
        server = protocol.Server({"echo": lambda p, c: _echo_handler(
            "echo", p, c)})
        port = await server.start_tcp("127.0.0.1", 0)
        conn = await protocol.connect(f"127.0.0.1:{port}")
        assert await conn.call("echo", {"x": 1}) == {"x": 1}
        task = conn._task
        assert not task.done()
        await conn.aclose()
        assert task.done()
        server.close()
        return True

    assert io.run(scenario())


def test_event_loop_thread_stop_drains_pending_tasks():
    t = protocol.EventLoopThread(name="drain-io")

    async def hang_forever():
        await asyncio.Event().wait()

    futs = [t.run_async(hang_forever()) for _ in range(5)]
    t.stop()
    assert t.loop.is_closed()
    for f in futs:
        assert f.done()  # cancelled by the drain, not abandoned
    # a second stop is a no-op, not a drain scheduled onto a dead loop
    t.stop()
    gc.collect()  # would emit "Task was destroyed" if the drain missed any


def test_single_flight_connect_dedups_racing_dials(io):
    async def scenario():
        server = protocol.Server({"echo": lambda p, c: _echo_handler(
            "echo", p, c)})
        port = await server.start_tcp("127.0.0.1", 0)
        cache, pending, dials = {}, {}, []

        async def dial(addr):
            dials.append(addr)
            await asyncio.sleep(0.01)  # hold the dial open so callers pile up
            return await protocol.connect(addr)

        conns = await asyncio.gather(*[
            protocol.single_flight_connect(
                cache, pending, f"127.0.0.1:{port}", dial)
            for _ in range(20)])
        assert len(dials) == 1  # one leader, 19 waiters
        assert all(c is conns[0] for c in conns)
        assert not pending
        await conns[0].aclose()
        server.close()
        return True

    assert io.run(scenario())


def test_single_flight_failed_leader_lets_waiter_retry(io):
    async def scenario():
        cache, pending = {}, {}
        attempts = []

        async def dial(addr):
            attempts.append(addr)
            if len(attempts) == 1:
                await asyncio.sleep(0.01)
                raise ConnectionError("first dial refused")
            server = protocol.Server({})
            port = await server.start_tcp("127.0.0.1", 0)
            return await protocol.connect(f"127.0.0.1:{port}")

        results = await asyncio.gather(*[
            protocol.single_flight_connect(cache, pending, "fake:1", dial)
            for _ in range(4)], return_exceptions=True)
        # the leader saw its own ConnectionError; a waiter retried as
        # leader and the rest shared its successful dial
        errs = [r for r in results if isinstance(r, Exception)]
        conns = [r for r in results if isinstance(r, protocol.Connection)]
        assert len(errs) == 1 and isinstance(errs[0], ConnectionError)
        assert len(conns) == 3 and all(c is conns[0] for c in conns)
        assert len(attempts) == 2
        await conns[0].aclose()
        return True

    assert io.run(scenario())


def test_single_flight_waiter_cancellation_propagates(io):
    async def scenario():
        cache, pending = {}, {}
        started = asyncio.Event()

        async def dial(addr):
            started.set()
            await asyncio.sleep(5)
            raise AssertionError("dial should have been abandoned")

        leader = asyncio.ensure_future(
            protocol.single_flight_connect(cache, pending, "fake:2", dial))
        await started.wait()
        waiter = asyncio.ensure_future(
            protocol.single_flight_connect(cache, pending, "fake:2", dial))
        await asyncio.sleep(0.01)
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        leader.cancel()
        with pytest.raises(asyncio.CancelledError):
            await leader
        assert not pending  # leader unwound its single-flight slot
        return True

    assert io.run(scenario())
