"""Shutdown hygiene of the RPC plane (reference: the reference's
core_worker/raylet destructors join their io_service threads —
src/ray/common/asio/ — so no pending handler outlives its loop).

These are the regression tests for the round-4 verdict item "every
long-lived process sprays 'Task was destroyed but it is pending!' on
shutdown": Connection.close() must cancel its read loop, aclose() must
wait for the unwind, EventLoopThread.stop() must drain every pending
task before closing the loop, and single-flight dialing must never
leak a raced Connection.
"""

import asyncio
import gc

import pytest

from ray_tpu._private import protocol


async def _echo_handler(method, payload, conn):
    return payload


@pytest.fixture
def io():
    t = protocol.EventLoopThread(name="test-io")
    yield t
    t.stop()


def test_connection_close_cancels_read_loop(io):
    async def scenario():
        server = protocol.Server({"echo": lambda p, c: _echo_handler(
            "echo", p, c)})
        port = await server.start_tcp("127.0.0.1", 0)
        conn = await protocol.connect(f"127.0.0.1:{port}")
        assert await conn.call("echo", {"x": 1}) == {"x": 1}
        task = conn._task
        assert not task.done()
        await conn.aclose()
        assert task.done()
        server.close()
        return True

    assert io.run(scenario())


def test_event_loop_thread_stop_drains_pending_tasks():
    t = protocol.EventLoopThread(name="drain-io")

    async def hang_forever():
        await asyncio.Event().wait()

    futs = [t.run_async(hang_forever()) for _ in range(5)]
    t.stop()
    assert t.loop.is_closed()
    for f in futs:
        assert f.done()  # cancelled by the drain, not abandoned
    # a second stop is a no-op, not a drain scheduled onto a dead loop
    t.stop()
    gc.collect()  # would emit "Task was destroyed" if the drain missed any


def test_single_flight_connect_dedups_racing_dials(io):
    async def scenario():
        server = protocol.Server({"echo": lambda p, c: _echo_handler(
            "echo", p, c)})
        port = await server.start_tcp("127.0.0.1", 0)
        cache, pending, dials = {}, {}, []

        async def dial(addr):
            dials.append(addr)
            await asyncio.sleep(0.01)  # hold the dial open so callers pile up
            return await protocol.connect(addr)

        conns = await asyncio.gather(*[
            protocol.single_flight_connect(
                cache, pending, f"127.0.0.1:{port}", dial)
            for _ in range(20)])
        assert len(dials) == 1  # one leader, 19 waiters
        assert all(c is conns[0] for c in conns)
        assert not pending
        await conns[0].aclose()
        server.close()
        return True

    assert io.run(scenario())


def test_single_flight_failed_leader_lets_waiter_retry(io):
    async def scenario():
        cache, pending = {}, {}
        attempts = []

        async def dial(addr):
            attempts.append(addr)
            if len(attempts) == 1:
                await asyncio.sleep(0.01)
                raise ConnectionError("first dial refused")
            server = protocol.Server({})
            port = await server.start_tcp("127.0.0.1", 0)
            return await protocol.connect(f"127.0.0.1:{port}")

        results = await asyncio.gather(*[
            protocol.single_flight_connect(cache, pending, "fake:1", dial)
            for _ in range(4)], return_exceptions=True)
        # the leader saw its own ConnectionError; a waiter retried as
        # leader and the rest shared its successful dial
        errs = [r for r in results if isinstance(r, Exception)]
        conns = [r for r in results if isinstance(r, protocol.Connection)]
        assert len(errs) == 1 and isinstance(errs[0], ConnectionError)
        assert len(conns) == 3 and all(c is conns[0] for c in conns)
        assert len(attempts) == 2
        await conns[0].aclose()
        return True

    assert io.run(scenario())


def test_single_flight_waiter_cancellation_propagates(io):
    async def scenario():
        cache, pending = {}, {}
        started = asyncio.Event()

        async def dial(addr):
            started.set()
            await asyncio.sleep(5)
            raise AssertionError("dial should have been abandoned")

        leader = asyncio.ensure_future(
            protocol.single_flight_connect(cache, pending, "fake:2", dial))
        await started.wait()
        waiter = asyncio.ensure_future(
            protocol.single_flight_connect(cache, pending, "fake:2", dial))
        await asyncio.sleep(0.01)
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        leader.cancel()
        with pytest.raises(asyncio.CancelledError):
            await leader
        assert not pending  # leader unwound its single-flight slot
        return True

    assert io.run(scenario())


def test_stop_after_loop_thread_exit_is_clean():
    """stop() on an EventLoopThread whose loop thread already exited
    must not schedule the drain onto the dead loop — the coroutine
    would never be awaited (flagged at GC) and the loop never closed."""
    import warnings

    t = protocol.EventLoopThread(name="dead-io")
    # simulate a crashed/early-exited loop thread
    t.loop.call_soon_threadsafe(t.loop.stop)
    t._thread.join(timeout=5)
    assert not t._thread.is_alive()
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # "never awaited"
        t.stop()
        gc.collect()
    assert t.loop.is_closed()
    t.stop()  # second call stays a no-op


def test_hello_records_peer_version(io):
    """__hello__ stores what the peer negotiated in conn.meta so
    handlers can gate minor-version features on it."""
    from ray_tpu._private import schema

    async def scenario():
        server = protocol.Server({})
        port = await server.start_tcp("127.0.0.1", 0)
        conn = await protocol.connect(f"127.0.0.1:{port}")
        reply = await conn.call("__hello__", schema.hello_payload())
        assert reply["protocol_version"] == list(schema.PROTOCOL_VERSION)
        sconn = next(iter(server.connections))
        assert sconn.meta["peer_protocol_version"] == \
            schema.PROTOCOL_VERSION
        await conn.aclose()
        server.close()
        return True

    assert io.run(scenario())


def test_dispatch_status_batch_gated_on_peer_minor(io):
    """A peer that never negotiated >=1.1 gets per-task
    task_dispatch_status notifies; a 1.1+ peer gets the coalesced
    batch."""
    import types

    from ray_tpu._private.raylet import Raylet

    async def scenario():
        sent = []

        class FakeConn:
            def __init__(self, meta):
                self.meta = meta

            async def notify(self, method, payload):
                sent.append((self.meta.get("tag"), method, payload))

        legacy = FakeConn({"tag": "legacy"})  # no hello ever
        old = FakeConn({"tag": "old",
                        "peer_protocol_version": (1, 0)})
        modern = FakeConn({"tag": "modern",
                           "peer_protocol_version": (1, 1)})
        fake = types.SimpleNamespace(
            _dispatch_status_flush_scheduled=True,
            _dispatch_status_buf={
                1: (legacy, [{"task_id": "a"}, {"task_id": "b"}]),
                2: (old, [{"task_id": "c"}]),
                3: (modern, [{"task_id": "d"}, {"task_id": "e"}]),
            })
        Raylet._flush_dispatch_statuses(fake)
        await asyncio.sleep(0.05)
        by_tag = {}
        for tag, method, payload in sent:
            by_tag.setdefault(tag, []).append((method, payload))
        assert by_tag["legacy"] == [
            ("task_dispatch_status", {"task_id": "a"}),
            ("task_dispatch_status", {"task_id": "b"})]
        assert by_tag["old"] == [
            ("task_dispatch_status", {"task_id": "c"})]
        assert by_tag["modern"] == [
            ("task_dispatch_status_batch",
             {"statuses": [{"task_id": "d"}, {"task_id": "e"}]})]
        return True

    assert io.run(scenario())
