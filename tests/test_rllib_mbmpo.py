"""MBMPO learning test (reference: rllib/algorithms/mbmpo/ — model
ensemble + MAML over ensemble members as tasks)."""

import numpy as np


def test_mbmpo_learns_point_goal():
    from ray_tpu.rllib.algorithms import MBMPO

    algo = MBMPO(config={
        "seed": 0,
        "ensemble_size": 3,
        "real_episodes_per_iter": 12,
        "imagined_episodes": 12,
        "model_train_iters": 40,
        "horizon": 20,
        "lr": 3e-3,
    })
    try:
        first = algo.train()
        assert np.isfinite(first["model_loss"])
        best = -np.inf
        for _ in range(14):
            res = algo.train()
            best = max(best, res["real_reward_mean"])
            # model must actually fit the simple dynamics
            if res["model_loss"] < 1e-3 and best > -12.0:
                break
        # random policy on point_goal scores ~ -19 (distance ~1 per
        # step over 20 steps); meta-trained + model-planned must beat it
        assert best > -14.0, f"no learning progress: best={best}"
        assert res["model_loss"] < 5e-2
    finally:
        algo.cleanup()
