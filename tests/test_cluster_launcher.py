"""Cluster launcher: YAML config validation, GCP TPU provider (offline
API client), ray-tpu up/down with the fake multinode provider.

Reference analogues: tests/test_autoscaler_yaml.py,
autoscaler/_private/gcp tests, test_cli (ray up) — scaled to one box.
"""

import json
import os
import time

import pytest

from ray_tpu.autoscaler.config import (ConfigError, make_provider,
                                       prepare_config)


# ----------------------------------------------------------- config


def _base_cfg(**over):
    cfg = {
        "cluster_name": "testc",
        "provider": {"type": "fake_multinode"},
        "available_node_types": {
            "head": {"resources": {"CPU": 2}},
            "worker": {"resources": {"CPU": 1}, "min_workers": 1},
        },
        "head_node_type": "head",
    }
    cfg.update(over)
    return cfg


def test_config_validation_errors():
    with pytest.raises(ConfigError, match="cluster_name"):
        prepare_config({"provider": {"type": "fake_multinode"},
                        "available_node_types": {"a": {}}})
    with pytest.raises(ConfigError, match="provider.type"):
        prepare_config(_base_cfg(provider={"type": "nonexistent_cloud"}))
    with pytest.raises(ConfigError, match="project_id"):
        prepare_config(_base_cfg(provider={"type": "gcp_tpu"}))
    with pytest.raises(ConfigError, match="min_workers"):
        prepare_config(_base_cfg(available_node_types={
            "head": {"min_workers": 9, "max_workers": 2}}))
    with pytest.raises(ConfigError, match="head_node_type"):
        prepare_config(_base_cfg(head_node_type="nope"))
    cfg = prepare_config(_base_cfg())
    assert cfg["available_node_types"]["worker"]["max_workers"] == 8


# ------------------------------------------------------ gcp provider


class FakeTPUApi:
    """Offline stand-in for the Cloud TPU queuedResources REST API."""

    def __init__(self):
        self.qrs = {}
        self.calls = []

    def request(self, method, path, body=None):
        self.calls.append((method, path))
        if method == "POST":
            name = path.split("queuedResourceId=")[1]
            self.qrs[name] = {"name": f"projects/p/locations/z/"
                                      f"queuedResources/{name}",
                              "state": {"state": "WAITING_FOR_RESOURCES"},
                              "body": body,
                              # the real API echoes the spec on GET
                              "tpu": (body or {}).get("tpu", {})}
            return {"name": f"operations/{name}"}
        if method == "GET" and path == "queuedResources":
            return {"queuedResources": list(self.qrs.values())}
        if method == "GET":
            name = path.split("/")[-1].split("?")[0]
            return self.qrs.get(name, {})
        if method == "DELETE":
            name = path.split("/")[-1].split("?")[0]
            self.qrs.pop(name, None)
            return {}
        raise AssertionError(f"unexpected {method} {path}")


def test_gcp_tpu_provider_lifecycle():
    from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider
    api = FakeTPUApi()
    p = GCPTPUNodeProvider(
        {"project_id": "proj", "availability_zone": "us-central2-b",
         "cluster_name": "mycl"}, api_client=api)
    ids = p.create_node({"acceleratorType": "v5litepod-8",
                         "reserved": True}, 2)
    assert len(ids) == 2 and all(i.startswith("mycl-") for i in ids)
    # request body carries the slice spec
    body = api.qrs[ids[0]]["body"]
    node = body["tpu"]["nodeSpec"][0]["node"]
    assert node["acceleratorType"] == "v5litepod-8"
    assert body.get("guaranteed", {}).get("reserved") is True
    assert sorted(p.non_terminated_nodes()) == sorted(ids)
    # whole-slice resources: 8 chips over 2 hosts
    res = p.node_resources(ids[0])
    assert res["TPU"] == 8.0 and res["tpu_slice"] == 1.0
    assert p.node_state(ids[0]) == "WAITING_FOR_RESOURCES"
    p.terminate_node(ids[0])
    assert p.non_terminated_nodes() == [ids[1]]
    # foreign queued resources are not ours
    api.qrs["other-abc"] = {"name": ".../other-abc",
                            "state": {"state": "ACTIVE"}}
    assert "other-abc" not in p.non_terminated_nodes()
    # a FRESH provider (monitor restart) recovers slice resources from
    # the API instead of reporting a zero-capacity cluster
    from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider as P2
    p2 = P2({"project_id": "proj", "availability_zone": "us-central2-b",
             "cluster_name": "mycl"}, api_client=api)
    assert p2.node_resources(ids[1])["TPU"] == 8.0


def test_gcp_up_down_via_commands(tmp_path, monkeypatch):
    from ray_tpu.autoscaler import commands
    monkeypatch.setattr(commands, "STATE_DIR", str(tmp_path))
    api = FakeTPUApi()
    cfg = _base_cfg(
        cluster_name="gcpc",
        provider={"type": "gcp_tpu", "project_id": "proj",
                  "availability_zone": "us-central2-b"},
        available_node_types={
            "head": {"resources": {"TPU": 8},
                     "node_config": {"acceleratorType": "v5litepod-8"}},
            "pod": {"min_workers": 2,
                    "node_config": {"acceleratorType": "v5litepod-16"}},
        })
    state = commands.create_or_update_cluster(cfg, api_client=api)
    # head slice + 2 worker slices requested
    assert len(state["nodes"]) == 3
    assert len(api.qrs) == 3
    # IDEMPOTENT: a second `up` reconciles, requests nothing new
    state2 = commands.create_or_update_cluster(cfg, api_client=api)
    assert len(state2["nodes"]) == 3
    assert len(api.qrs) == 3
    n = commands.teardown_cluster(cfg, api_client=api)
    assert n == 3
    assert not api.qrs


# ------------------------------------------------- fake multinode up


@pytest.mark.slow
def test_up_down_fake_multinode(tmp_path, monkeypatch):
    from ray_tpu.autoscaler import commands
    monkeypatch.setattr(commands, "STATE_DIR", str(tmp_path))
    cfg = _base_cfg(cluster_name="fakeup")
    state = commands.create_or_update_cluster(cfg)
    try:
        assert state["head"]["gcs_address"]
        assert len(state["nodes"]) == 1
        # a fresh driver can join the launched cluster and see both nodes
        import ray_tpu
        ray_tpu.init(address=state["head"]["gcs_address"])
        deadline = time.time() + 60
        alive = []
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= 2:
                break
            time.sleep(1.0)
        assert len(alive) >= 2, alive

        @ray_tpu.remote
        def f():
            return 7

        assert ray_tpu.get(f.remote(), timeout=60) == 7
        ray_tpu.shutdown()
    finally:
        n = commands.teardown_cluster(cfg)
    assert n >= 2
    # state file removed
    assert commands._load_state("fakeup") is None
