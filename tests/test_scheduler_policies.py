"""Scheduler policies on a multi-node cluster: locality-aware spillback,
node affinity strict/soft, spread.

Reference analogues: test_scheduling.py locality tests (lease_policy),
test_actor_distribution (affinity).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture(scope="module")
def two_worker_cluster():
    from ray_tpu._private.cluster_utils import Cluster
    # head runs the driver only (no CPUs): every task spills back through
    # the GCS scheduler, which is the policy under test
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 0})
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address, ignore_reinit_error=True)
    deadline = time.time() + 60
    while time.time() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["alive"]) >= 3:
            break
        time.sleep(0.5)
    yield cluster, n1["node_id"], n2["node_id"]
    ray_tpu.shutdown()
    cluster.shutdown()


def test_node_affinity_strict_and_soft(two_worker_cluster):
    _, n1, n2 = two_worker_cluster

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    for target in (n1, n2):
        got = ray_tpu.get(where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=target)).remote(), timeout=60)
        assert got == target
    # soft affinity to a dead node id still schedules somewhere
    got = ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="0" * 32, soft=True)).remote(), timeout=60)
    assert got in (n1, n2)


def test_locality_aware_spillback(two_worker_cluster):
    _, n1, n2 = two_worker_cluster

    @ray_tpu.remote
    def produce():
        # big enough for plasma (not inline)
        return np.ones((512, 512), np.float32)

    @ray_tpu.remote
    def consume(arr):
        assert arr.shape == (512, 512)
        return ray_tpu.get_runtime_context().get_node_id()

    # place the dependency's primary copy deterministically on n1
    dep = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=n1)).remote()
    ray_tpu.wait([dep], num_returns=1, timeout=60)
    # the location registers with the GCS directory at pin time, a beat
    # after the owner sees readiness — poll so the policy has its input
    from ray_tpu._private import worker as wm
    w = wm.global_worker()
    deadline = time.time() + 30
    node_ids: list = []
    while time.time() < deadline:
        locs = w.call_sync(w.gcs, "get_object_locations",
                           {"object_id": dep.id().hex()})
        node_ids = [loc["node_id"]
                    for loc in (locs.get("locations") or [])]
        if n1 in node_ids:
            break
        time.sleep(0.2)
    assert n1 in node_ids, locs
    # unpinned consumers spill through the GCS: locality must beat the
    # (equally utilized) other node. A short gap between consumers lets
    # the event-driven release report land — back-to-back submits can
    # legitimately overflow to the other node while the dep holder's
    # last placement is still in flight (pessimistic accounting).
    hits = []
    for _ in range(4):
        hits.append(ray_tpu.get(consume.remote(dep), timeout=60))
        time.sleep(0.4)
    # dominant preference, not perfection: one consumer may overflow to
    # the other node while the holder's last placement is still in the
    # pessimistic window (and its fetch then makes a REAL second copy,
    # legitimately tying locality afterwards)
    assert hits.count(n1) >= 3, hits


def test_spread_distributes(two_worker_cluster):
    _, n1, n2 = two_worker_cluster

    @ray_tpu.remote
    def where():
        import time as _t
        _t.sleep(0.3)
        return ray_tpu.get_runtime_context().get_node_id()

    refs = [where.options(scheduling_strategy="SPREAD").remote()
            for _ in range(4)]
    got = set(ray_tpu.get(refs, timeout=60))
    assert got == {n1, n2}
