"""jax.profiler → framework-timeline integration (SURVEY §5.1: keep
the chrome-trace timeline; integrate jax.profiler/xplane traces per
worker and merge by host)."""

import numpy as np
import pytest

import ray_tpu


def test_trace_merges_xla_events_into_local_timeline():
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import timeline, tpu_profiler

    @jax.jit
    def f(x):
        return x @ x

    with tpu_profiler.trace(label="xla-test") as d:
        x = jnp.ones((128, 128))
        for _ in range(3):
            x = f(x)
        x.block_until_ready()
    # raw artifacts exist for TensorBoard
    assert tpu_profiler.load_chrome_events(d)
    evs = timeline.collect()
    xla = [e for e in evs if e.get("cat") == "xla-test"]
    assert xla, "no XLA events merged"
    names = [e for e in evs if e.get("name") == "process_name"
             and "xla-test" in str(e.get("args"))]
    assert names, "XLA process rows not labeled"
    # rebased to wall-clock: within an hour of now, not a raw steady-
    # clock offset
    import time
    now_us = time.time() * 1e6
    assert all(abs(e["ts"] - now_us) < 3600e6 for e in xla)


def test_trace_events_reach_driver_timeline_dump():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def traced_work():
            import jax
            import jax.numpy as jnp

            from ray_tpu.util import tpu_profiler

            @jax.jit
            def g(x):
                return (x * x).sum()

            with tpu_profiler.trace(label="xla-task"):
                v = g(jnp.arange(64, dtype=jnp.float32))
                float(v)
            from ray_tpu.util import timeline
            timeline.flush()
            return True

        assert ray_tpu.get(traced_work.remote(), timeout=120)
        import time
        deadline = time.time() + 15
        merged = []
        while time.time() < deadline:
            merged = [e for e in ray_tpu.timeline()
                      if e.get("cat") == "xla-task"]
            if merged:
                break
            time.sleep(1.0)
        assert merged, "worker XLA capture did not reach the merged dump"
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------- unit layer
# load/rebase/merge units (ISSUE 13 satellite): previously only the
# jax-integration paths above exercised these; synthetic captures pin
# the contract each piece owns.


def _write_capture(log_dir, rel_path, events):
    import gzip
    import json
    import os
    path = os.path.join(log_dir, rel_path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_load_chrome_events_walks_nested_captures(tmp_path):
    from ray_tpu.util import tpu_profiler
    _write_capture(str(tmp_path), "plugins/profile/run1/h1.trace.json.gz",
                   [{"name": "a", "ph": "X", "ts": 10.0, "dur": 5.0,
                     "pid": 1, "tid": 0}])
    _write_capture(str(tmp_path), "plugins/profile/run1/h2.trace.json.gz",
                   [{"name": "b", "ph": "X", "ts": 20.0, "dur": 7.0,
                     "pid": 2, "tid": 0}])
    # non-matching files are ignored
    (tmp_path / "notes.json").write_text("{}")
    evs = tpu_profiler.load_chrome_events(str(tmp_path))
    assert {e["name"] for e in evs} == {"a", "b"}
    assert tpu_profiler.load_chrome_events(str(tmp_path / "empty")) == []


def test_merge_rebases_to_wall_clock_and_filters(tmp_path):
    """Rebase: the capture's steady-clock ts land at wall_start_us +
    (ts - min ts); sub-floor spans and the per-capture cap apply."""
    from ray_tpu.util import timeline, tpu_profiler
    events = [
        {"name": "big", "ph": "X", "ts": 1000.0, "dur": 100.0,
         "pid": 7, "tid": 3},
        {"name": "later", "ph": "X", "ts": 1500.0, "dur": 50.0,
         "pid": 7, "tid": 3},
        {"name": "tiny", "ph": "X", "ts": 1200.0, "dur": 0.5,
         "pid": 7, "tid": 3},  # below min_dur_us
        {"name": "meta", "ph": "M", "ts": 0.0, "pid": 7},  # not 'X'
    ]
    wall = 1_700_000_000 * 1e6
    before = len(timeline.collect())
    n = tpu_profiler.merge_into_timeline(
        events, wall_start_us=wall, label="unit-xla", min_dur_us=5.0)
    assert n == 2
    merged = [e for e in timeline.collect()[before:]
              if e.get("cat") == "unit-xla"]
    by_name = {e["name"]: e for e in merged}
    assert set(by_name) == {"big", "later"}
    assert by_name["big"]["ts"] == wall          # min ts -> wall start
    assert by_name["later"]["ts"] == wall + 500.0
    # cap keeps the LONGEST spans, not the first ones
    many = [{"name": f"s{i}", "ph": "X", "ts": float(i),
             "dur": float(i + 1), "pid": 1, "tid": 0}
            for i in range(50)]
    before = len(timeline.collect())
    n = tpu_profiler.merge_into_timeline(
        many, wall_start_us=wall, label="unit-cap", max_events=10,
        min_dur_us=0.0)
    assert n == 10
    kept = [e for e in timeline.collect()[before:]
            if e.get("cat") == "unit-cap"]
    assert {e["name"] for e in kept} == {f"s{i}" for i in range(40, 50)}


def test_merge_xla_pid_rows_are_stable_and_separated():
    """_XLA_PID_BASE row mapping: XLA process rows never collide with
    framework task pids, distinct source pids get distinct rows, and
    the digest is restart-stable (same node+pid -> same row)."""
    from ray_tpu.util import timeline, tpu_profiler
    events = [{"name": "x", "ph": "X", "ts": 1.0, "dur": 10.0,
               "pid": 11, "tid": 0},
              {"name": "y", "ph": "X", "ts": 2.0, "dur": 10.0,
               "pid": 22, "tid": 0}]
    before = len(timeline.collect())
    tpu_profiler.merge_into_timeline(
        events, wall_start_us=0.0, label="unit-rows", min_dur_us=0.0)
    first = [e for e in timeline.collect()[before:]
             if e.get("cat") == "unit-rows"]
    pids1 = {e["name"]: e["pid"] for e in first}
    assert pids1["x"] != pids1["y"]
    assert all(p >= tpu_profiler._XLA_PID_BASE for p in pids1.values())
    # process_name metadata labels each synthetic row
    metas = [e for e in timeline.collect()[before:]
             if e.get("name") == "process_name"
             and "unit-rows" in str(e.get("args"))]
    assert len(metas) == 2
    # stability: a second merge (fresh seen_pids map) lands on the
    # same rows — crc32 digest, not Python's randomized hash()
    before = len(timeline.collect())
    tpu_profiler.merge_into_timeline(
        events, wall_start_us=0.0, label="unit-rows", min_dur_us=0.0)
    second = [e for e in timeline.collect()[before:]
              if e.get("cat") == "unit-rows" and e.get("ph") == "X"]
    pids2 = {e["name"]: e["pid"] for e in second}
    assert pids2 == pids1
    timeline.stop_flusher()
