"""jax.profiler → framework-timeline integration (SURVEY §5.1: keep
the chrome-trace timeline; integrate jax.profiler/xplane traces per
worker and merge by host)."""

import numpy as np
import pytest

import ray_tpu


def test_trace_merges_xla_events_into_local_timeline():
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import timeline, tpu_profiler

    @jax.jit
    def f(x):
        return x @ x

    with tpu_profiler.trace(label="xla-test") as d:
        x = jnp.ones((128, 128))
        for _ in range(3):
            x = f(x)
        x.block_until_ready()
    # raw artifacts exist for TensorBoard
    assert tpu_profiler.load_chrome_events(d)
    evs = timeline.collect()
    xla = [e for e in evs if e.get("cat") == "xla-test"]
    assert xla, "no XLA events merged"
    names = [e for e in evs if e.get("name") == "process_name"
             and "xla-test" in str(e.get("args"))]
    assert names, "XLA process rows not labeled"
    # rebased to wall-clock: within an hour of now, not a raw steady-
    # clock offset
    import time
    now_us = time.time() * 1e6
    assert all(abs(e["ts"] - now_us) < 3600e6 for e in xla)


def test_trace_events_reach_driver_timeline_dump():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def traced_work():
            import jax
            import jax.numpy as jnp

            from ray_tpu.util import tpu_profiler

            @jax.jit
            def g(x):
                return (x * x).sum()

            with tpu_profiler.trace(label="xla-task"):
                v = g(jnp.arange(64, dtype=jnp.float32))
                float(v)
            from ray_tpu.util import timeline
            timeline.flush()
            return True

        assert ray_tpu.get(traced_work.remote(), timeout=120)
        import time
        deadline = time.time() + 15
        merged = []
        while time.time() < deadline:
            merged = [e for e in ray_tpu.timeline()
                      if e.get("cat") == "xla-task"]
            if merged:
                break
            time.sleep(1.0)
        assert merged, "worker XLA capture did not reach the merged dump"
    finally:
        ray_tpu.shutdown()
