"""L5 ops-layer tests: state API, jobs, dashboard HTTP, autoscaler, CLI.
(reference strategy: dashboard/modules/job tests, autoscaler fake-node
tests — SURVEY.md §4 'fake node provider for autoscaler logic')."""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu


def test_state_api(ray_start_shared):
    from ray_tpu.experimental.state import (list_actors, list_nodes,
                                            summarize_cluster)

    @ray_tpu.remote
    class Marker:
        def ping(self):
            return "ok"

    m = Marker.options(name="state-marker").remote()
    ray_tpu.get(m.ping.remote())
    nodes = list_nodes()
    assert len(nodes) >= 1 and nodes[0]["alive"]
    actors = list_actors()
    assert any(a.get("name") == "state-marker" for a in actors)
    s = summarize_cluster()
    assert s["nodes_alive"] >= 1
    assert s["cluster_resources"].get("CPU", 0) > 0


def test_job_submission_in_cluster(ray_start_shared):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint="echo hello-from-job && echo err-line >&2")
    status = client.wait_until_finish(job_id, timeout=60)
    assert status == JobStatus.SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "hello-from-job" in logs
    assert "err-line" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(ray_start_shared):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="exit 3")
    assert client.wait_until_finish(job_id, timeout=60) == \
        JobStatus.FAILED
    assert client.get_job_info(job_id)["return_code"] == 3


def test_job_stop(ray_start_shared):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="sleep 120")
    time.sleep(0.5)
    client.stop_job(job_id)
    deadline = time.time() + 30
    while time.time() < deadline:
        if client.get_job_status(job_id) == JobStatus.STOPPED:
            break
        time.sleep(0.2)
    assert client.get_job_status(job_id) == JobStatus.STOPPED


def test_dashboard_http(ray_start_shared):
    from ray_tpu.dashboard import start_dashboard
    port = start_dashboard(port=8270)

    def get(path):
        return json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read())

    assert urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz",
        timeout=30).read() == b"ok"
    status = get("/api/cluster_status")
    assert status["nodes_alive"] >= 1
    nodes = get("/api/nodes")["nodes"]
    assert len(nodes) >= 1
    # job submit through REST
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/jobs/",
        data=json.dumps({"entrypoint": "echo via-rest"}).encode(),
        headers={"Content-Type": "application/json"})
    job_id = json.loads(
        urllib.request.urlopen(r, timeout=60).read())["job_id"]
    deadline = time.time() + 60
    while time.time() < deadline:
        info = get(f"/api/jobs/{job_id}")
        if info["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.3)
    assert info["status"] == "SUCCEEDED"
    assert "via-rest" in get(f"/api/jobs/{job_id}/logs")["logs"]
    # prometheus endpoint responds
    urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                           timeout=30).read()


def test_timeline_records_tasks(ray_start_shared):
    from ray_tpu.util.timeline import timeline_dump

    @ray_tpu.remote
    def traced():
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    time.sleep(3.0)  # wait for the workers' background flushers
    events = timeline_dump()
    task_events = [e for e in events
                   if e.get("cat") == "task" and "traced" in
                   str(e.get("name"))]
    assert len(task_events) >= 1


def test_cli_help_and_status():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "--help"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "job" in out.stdout and "start" in out.stdout
