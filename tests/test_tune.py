"""Tune kernel tests (reference analogues: tune/tests/test_api.py,
test_trial_scheduler.py — scaled down to the 1-box CI)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import session


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_function_trainable_grid(cluster):
    def train_fn(config):
        for i in range(3):
            session.report({"score": config["a"] * 10 + i})

    analysis = tune.run(train_fn, config={"a": tune.grid_search([1, 2, 3])},
                        metric="score", mode="max", max_concurrent_trials=3)
    assert len(analysis.trials) == 3
    best = analysis.best_trial
    assert best.config["a"] == 3
    assert analysis.best_result["score"] == 32
    assert all(t.status == "TERMINATED" for t in analysis.trials)


def test_class_trainable_and_stop_criteria(cluster):
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.x = config.get("start", 0)

        def step(self):
            self.x += 1
            return {"x": self.x}

        def save_checkpoint(self):
            return {"x": self.x}

        def load_checkpoint(self, state):
            self.x = state["x"]

    analysis = tune.run(MyTrainable, config={"start": 5},
                        stop={"training_iteration": 4},
                        metric="x", mode="max")
    t = analysis.trials[0]
    assert t.last_result["x"] == 9
    assert t.last_result["training_iteration"] == 4


def test_asha_stops_bad_trials(cluster):
    def train_fn(config):
        for i in range(8):
            session.report({"score": config["q"] + i * 0.01})

    sched = tune.ASHAScheduler(metric="score", mode="max", max_t=8,
                               grace_period=1, reduction_factor=2)
    analysis = tune.run(train_fn,
                        config={"q": tune.grid_search([0.0, 0.0, 0.0, 100.0])},
                        metric="score", mode="max", scheduler=sched,
                        max_concurrent_trials=2)
    best = analysis.best_trial
    assert best.config["q"] == 100.0
    # at least one bad trial stopped before running all 8 iterations
    iters = [len(t.results) for t in analysis.trials
             if t.config["q"] == 0.0]
    assert min(iters) < 8, iters


def test_checkpoint_restore_on_failure(cluster):
    def train_fn(config):
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 6):
            from ray_tpu.air.checkpoint import Checkpoint
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))
            if i == 3 and start == 0:
                raise RuntimeError("boom")

    analysis = tune.run(train_fn, metric="i", mode="max", max_failures=1)
    t = analysis.trials[0]
    assert t.status == "TERMINATED"
    assert t.num_failures == 1
    assert t.last_result["i"] == 5
    # training_iteration keeps counting across the restart (4 results
    # pre-crash: i=0..3; then i=4,5 post-restore → 6 total)
    assert t.last_result["training_iteration"] == 6


def test_tuner_api_and_random_sampling(cluster):
    def train_fn(config):
        session.report({"v": config["lr"]})

    tuner = tune.Tuner(
        train_fn,
        param_space={"lr": tune.loguniform(1e-4, 1e-1)},
        tune_config=tune.TuneConfig(metric="v", mode="min", num_samples=4,
                                    max_concurrent_trials=2))
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    lrs = [r.metrics["v"] for r in grid]
    assert best.metrics["v"] == min(lrs)
    assert 1e-4 <= best.metrics["v"] <= 1e-1


def test_pbt_exploit(cluster):
    def train_fn(config):
        ckpt = session.get_checkpoint()
        score = ckpt.to_dict()["score"] if ckpt else 0.0
        lr = config["lr"]
        for i in range(10):
            score += lr
            from ray_tpu.air.checkpoint import Checkpoint
            session.report({"score": score},
                           checkpoint=Checkpoint.from_dict({"score": score}))

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": tune.uniform(0.1, 1.0)}, seed=0)
    analysis = tune.run(train_fn,
                        config={"lr": tune.grid_search([0.01, 1.0])},
                        metric="score", mode="max", scheduler=pbt,
                        max_concurrent_trials=2)
    assert len(analysis.trials) == 2
    assert all(t.status == "TERMINATED" for t in analysis.trials)
    # exploit copied the strong trial's progress into the weak one, so the
    # weak trial's final score must beat its solo trajectory (10 * 0.01)
    weak = [t for t in analysis.trials if t.config.get("lr") != 1.0]
    if weak:  # config may have been mutated away from 0.01
        assert weak[0].last_result["score"] > 0.2


def test_trials_exceed_cluster_cpus(cluster):
    """Regression: _start_trial used to block on ray_tpu.get(create),
    deadlocking the runner the moment pending trials exceeded free CPUs
    (the pending actor's resources are held by running trials whose
    results only the blocked runner can process)."""
    def train_fn(config):
        for i in range(3):
            session.report({"score": config["x"] * (i + 1)})

    analysis = tune.run(train_fn,
                        config={"x": tune.grid_search(list(range(1, 11)))},
                        metric="score", mode="max", verbose=0)
    assert len(analysis.trials) == 10
    bad = [(t.trial_id, t.status, (t.error or "")[:500])
           for t in analysis.trials if t.status != "TERMINATED"]
    assert not bad, f"non-terminated trials: {bad}"
    assert analysis.get_best_trial().last_result["score"] == 30
