"""AWS/Azure providers + SSH/docker updater, driven with injected fakes.

Reference analogues: autoscaler/_private/aws/node_provider.py,
_azure/node_provider.py, command_runner.py, updater.py — tested the
way the GCE TPU provider is: a fake transport/binary stands in for the
cloud, so the provider/updater LOGIC runs for real.
"""

import os
import stat

import pytest

from ray_tpu.autoscaler.aws import AWSNodeProvider
from ray_tpu.autoscaler.azure import AzureNodeProvider
from ray_tpu.autoscaler.command_runner import (DockerCommandRunner,
                                               SSHCommandRunner)
from ray_tpu.autoscaler.config import (ConfigError, make_provider,
                                        prepare_config, validate_config)
from ray_tpu.autoscaler.updater import NodeUpdateError, NodeUpdater


# ----------------------------------------------------------------- fakes

class FakeEC2:
    """Duck-typed boto3 ec2 client over an in-memory instance table."""

    def __init__(self):
        self.instances = {}
        self._n = 0

    def run_instances(self, **params):
        out = []
        for _ in range(params["MinCount"]):
            self._n += 1
            iid = f"i-{self._n:08x}"
            tags = params["TagSpecifications"][0]["Tags"]
            self.instances[iid] = {
                "InstanceId": iid, "State": "running",
                "InstanceType": params["InstanceType"],
                "Tags": tags, "PublicIpAddress": f"10.0.0.{self._n}"}
            out.append(self.instances[iid])
        return {"Instances": out}

    def describe_instances(self, Filters=None, InstanceIds=None):
        insts = list(self.instances.values())
        if InstanceIds:
            insts = [i for i in insts if i["InstanceId"] in InstanceIds]
        if Filters:
            for f in Filters:
                if f["Name"].startswith("tag:"):
                    key = f["Name"][4:]
                    insts = [i for i in insts
                             if any(t["Key"] == key
                                    and t["Value"] in f["Values"]
                                    for t in i["Tags"])]
                elif f["Name"] == "instance-state-name":
                    insts = [i for i in insts
                             if i["State"] in f["Values"]]
        return {"Reservations": [{"Instances": insts}]}

    def terminate_instances(self, InstanceIds):
        for iid in InstanceIds:
            self.instances.pop(iid, None)


class FakeAzureCompute:
    def __init__(self):
        self.vms = {}

    def list_vms(self, resource_group):
        return list(self.vms.values())

    def create_vm(self, resource_group, spec):
        self.vms[spec["name"]] = {**spec, "provisioning_state":
                                  "Succeeded",
                                  "public_ip":
                                      f"10.1.0.{len(self.vms) + 1}"}

    def delete_vm(self, resource_group, name):
        self.vms.pop(name, None)


# -------------------------------------------------------------- providers

def test_aws_provider_lifecycle():
    ec2 = FakeEC2()
    p = AWSNodeProvider({"region": "us-west-2",
                         "cluster_name": "c1"}, ec2_client=ec2)
    ids = p.create_node({"InstanceType": "m5.4xlarge",
                         "node_kind": "worker"}, 2)
    assert len(ids) == 2
    assert sorted(p.non_terminated_nodes()) == sorted(ids)
    assert p.node_resources(ids[0]) == {"CPU": 16.0}
    assert p.external_ip(ids[0]).startswith("10.0.0.")
    # other clusters' instances are invisible
    other = AWSNodeProvider({"region": "us-west-2",
                             "cluster_name": "c2"}, ec2_client=ec2)
    assert other.non_terminated_nodes() == []
    p.terminate_node(ids[0])
    assert p.non_terminated_nodes() == [ids[1]]


def test_azure_provider_lifecycle():
    az = FakeAzureCompute()
    p = AzureNodeProvider({"subscription_id": "s", "resource_group": "g",
                           "cluster_name": "c1"}, compute_client=az)
    ids = p.create_node({"vm_size": "Standard_D8s_v3"}, 2)
    assert len(ids) == 2 and all(i.startswith("c1-") for i in ids)
    assert sorted(p.non_terminated_nodes()) == sorted(ids)
    assert p.node_resources(ids[0]) == {"CPU": 8.0}
    assert p.external_ip(ids[0]).startswith("10.1.0.")
    p.terminate_node(ids[0])
    assert p.non_terminated_nodes() == [ids[1]]


def test_provider_registry_and_validation():
    base = {"cluster_name": "c", "max_workers": 4,
            "available_node_types": {"t": {"min_workers": 0}}}
    validate_config(prepare_config(
        {**base, "provider": {"type": "aws", "region": "us-east-1"}}))
    with pytest.raises(ConfigError, match="region"):
        validate_config(prepare_config(
            {**base, "provider": {"type": "aws"}}))
    with pytest.raises(ConfigError, match="subscription_id"):
        validate_config(prepare_config(
            {**base, "provider": {"type": "azure"}}))
    p = make_provider(
        {**base, "provider": {"type": "aws", "region": "r"}},
        ec2_client=FakeEC2())
    assert isinstance(p, AWSNodeProvider)
    p = make_provider(
        {**base, "provider": {"type": "azure", "subscription_id": "s",
                              "resource_group": "g"}},
        compute_client=FakeAzureCompute())
    assert isinstance(p, AzureNodeProvider)


def test_up_and_down_with_aws_fake(tmp_path, monkeypatch):
    from ray_tpu.autoscaler import commands
    monkeypatch.setattr(commands, "STATE_DIR", str(tmp_path))
    ec2 = FakeEC2()
    cfg = {"cluster_name": "awsup",
           "provider": {"type": "aws", "region": "r"},
           "head_node_type": "head",
           "available_node_types": {
               "head": {"min_workers": 0,
                        "node_config": {"InstanceType": "m5.xlarge"}},
               "cpu": {"min_workers": 2,
                       "node_config": {"InstanceType": "m5.large"}}}}
    state = commands.create_or_update_cluster(cfg, ec2_client=ec2)
    assert len(state["nodes"]) == 3  # 1 head + 2 workers
    # idempotent: a second up creates nothing new
    state = commands.create_or_update_cluster(cfg, ec2_client=ec2)
    assert len(state["nodes"]) == 3
    assert len(ec2.instances) == 3
    n = commands.teardown_cluster(cfg, ec2_client=ec2)
    assert n == 3 and not ec2.instances


# ------------------------------------------------------- runner + updater

@pytest.fixture
def fake_ssh(tmp_path):
    """An "ssh" that drops connection args and runs the command
    locally — the command after `--` is `bash -lc <cmd>`."""
    fake = tmp_path / "ssh"
    # mimics REAL ssh: the remote args are space-joined into one string
    # handed to the login shell (so quoting bugs surface here too)
    fake.write_text("""#!/bin/sh
while [ "$1" != "--" ]; do shift; done
shift
exec sh -c "$*"
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    return str(fake)


def test_ssh_runner_and_updater_phases(tmp_path, fake_ssh):
    marker = tmp_path / "order.txt"
    runner = SSHCommandRunner("1.2.3.4", user="u", ssh_binary=fake_ssh)
    rc, out = runner.run("echo hello")
    assert rc == 0 and "hello" in out

    upd = NodeUpdater(
        runner,
        initialization_commands=[f"echo init >> {marker}"],
        setup_commands=[f"echo setup >> {marker}"],
        start_commands=[f"echo start >> {marker}"])
    upd.update()
    assert marker.read_text().split() == ["init", "setup", "start"]
    assert upd.phases_done == ["wait_ready", "file_mounts",
                               "initialization_commands",
                               "setup_commands", "start_commands"]


def test_updater_failure_names_phase(fake_ssh):
    runner = SSHCommandRunner("1.2.3.4", ssh_binary=fake_ssh)
    upd = NodeUpdater(runner, setup_commands=["false"],
                      start_commands=["echo never"])
    with pytest.raises(NodeUpdateError) as ei:
        upd.update()
    assert ei.value.phase == "setup_commands"
    assert "start_commands" not in upd.phases_done


def test_docker_runner_wraps_commands(fake_ssh, tmp_path):
    log = tmp_path / "docker.log"
    fake_docker = tmp_path / "docker"
    fake_docker.write_text(f"""#!/bin/sh
echo "$@" >> {log}
case "$1" in inspect) exit 1;; esac
exit 0
""")
    fake_docker.chmod(fake_docker.stat().st_mode | stat.S_IEXEC)
    base = SSHCommandRunner("1.2.3.4", ssh_binary=fake_ssh)
    d = DockerCommandRunner(base, image="img:1",
                            docker_binary=str(fake_docker))
    assert d.ensure_container()[0] == 0
    assert d.run("echo inside")[0] == 0
    text = log.read_text()
    assert "run -d --name ray_tpu_container" in text
    assert "exec ray_tpu_container" in text


# ----------------------------------------------------- kubernetes/kuberay

class FakeK8s:
    def __init__(self):
        self.pods = {}

    def list_pods(self, namespace):
        return list(self.pods.values())

    def create_pod(self, namespace, pod):
        self.pods[pod["name"]] = {**pod, "phase": "Running"}

    def delete_pod(self, namespace, name):
        self.pods.pop(name, None)


def test_kubernetes_provider_and_operator_reconcile():
    from ray_tpu.autoscaler.kubernetes import (KubernetesNodeProvider,
                                               RayClusterOperator)
    k8s = FakeK8s()
    p = KubernetesNodeProvider({"namespace": "ns", "cluster_name": "c1"},
                               k8s_client=k8s)
    op = RayClusterOperator(p)
    spec = {"head": {"image": "img", "resources": {"CPU": 4}},
            "worker_groups": [
                {"name": "cpu", "replicas": 2, "resources": {"CPU": 2}},
                {"name": "tpu", "replicas": 1,
                 "resources": {"CPU": 8, "TPU": 4}}]}
    a = op.reconcile(spec)
    assert len(a["created"]) == 4 and not a["deleted"]  # 1 head + 3
    assert len(p.non_terminated_nodes()) == 4
    # idempotent second pass
    a = op.reconcile(spec)
    assert not a["created"] and not a["deleted"]
    # a dead worker pod is replaced
    cpu_pod = next(n for n in k8s.pods if "-cpu-" in n)
    k8s.pods[cpu_pod]["phase"] = "Failed"
    a = op.reconcile(spec)
    assert len(a["created"]) == 1
    # scale down + group removal
    spec["worker_groups"][0]["replicas"] = 1
    spec["worker_groups"].pop(1)  # drop the tpu group
    a = op.reconcile(spec)
    assert len(a["deleted"]) == 2  # one cpu scale-down + one tpu stray
    groups = {(pod["labels"]["ray-tpu.io/group"])
              for pod in k8s.pods.values() if pod["phase"] == "Running"}
    assert groups == {"head", "cpu"}
    assert p.node_resources(p.non_terminated_nodes()[0])
