"""Memory monitor / OOM protection.

Reference analogue: python/ray/tests/test_memory_pressure.py over
memory_monitor.h + worker_killing_policy.h (RetriableFIFO). The threshold is
driven to 0 via _system_config so ANY usage trips the monitor — the test
asserts the raylet (not the kernel) kills the worker and the owner sees a
retry/WorkerCrashedError with the OOM reason.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_oom_kills_running_task_worker():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=64 * 1024 * 1024,
                 _system_config={"memory_usage_threshold": 0.0,
                                 "memory_monitor_refresh_ms": 100,
                                 "prestart_workers": False})
    try:
        @ray_tpu.remote(max_retries=0)
        def hog():
            time.sleep(30)
            return 1

        with pytest.raises(exc.WorkerCrashedError, match="memory monitor"):
            ray_tpu.get(hog.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


def test_memory_fraction_sane():
    from ray_tpu._private.raylet import Raylet
    frac = Raylet._host_memory_fraction()
    assert 0.0 <= frac < 1.0
