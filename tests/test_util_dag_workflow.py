"""util / dag / workflow tests (reference strategy: ray/tests/test_actor_pool,
test_queue, dag tests, workflow/tests)."""

import os
import tempfile
import time

import pytest

import ray_tpu


def test_actor_pool_map(ray_start_shared):
    @ray_tpu.remote
    class Worker:
        def double(self, x):
            return x * 2

    from ray_tpu.util import ActorPool
    pool = ActorPool([Worker.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]
    out2 = sorted(pool.map_unordered(
        lambda a, v: a.double.remote(v), range(4)))
    assert out2 == [0, 2, 4, 6]


def test_queue_basic(ray_start_shared):
    from ray_tpu.util import Empty, Queue
    q = Queue(maxsize=4)
    q.put(1)
    q.put("two")
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == "two"
    with pytest.raises(Empty):
        q.get_nowait()


def test_queue_across_tasks(ray_start_shared):
    from ray_tpu.util import Queue
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return "done"

    ray_tpu.get(producer.remote(q, 3))
    assert [q.get(timeout=10) for _ in range(3)] == [0, 1, 2]


def test_multiprocessing_pool(ray_start_shared):
    from ray_tpu.util.multiprocessing import Pool
    with Pool(processes=2) as p:
        assert p.map(_sq, range(8)) == [x * x for x in range(8)]
        r = p.apply_async(_sq, (9,))
        assert r.get(timeout=30) == 81
        assert sorted(p.imap_unordered(_sq, [1, 2, 3])) == [1, 4, 9]


def _sq(x):
    return x * x


def test_metrics_roundtrip(ray_start_shared):
    from ray_tpu.util import metrics
    c = metrics.Counter("test_requests", description="reqs",
                        tag_keys=("route",))
    c.inc(1.0, tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("test_temp")
    g.set(42.0)
    h = metrics.Histogram("test_lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    deadline = time.time() + 15
    while time.time() < deadline:
        dump = {(m["name"], tuple(sorted(m["tags"].items()))): m
                for m in metrics.dump_metrics()}
        if (dump.get(("test_requests", (("route", "/a"),)), {})
                .get("value") == 3.0
                and ("test_lat", ()) in dump
                and dump[("test_lat", ())]["count"] == 3):
            break
        time.sleep(0.1)
    assert dump[("test_requests", (("route", "/a"),))]["value"] == 3.0
    assert dump[("test_temp", ())]["value"] == 42.0
    assert dump[("test_lat", ())]["count"] == 3
    text = metrics.prometheus_text()
    assert "test_requests" in text and "test_lat_bucket" in text


def test_dag_function_nodes(ray_start_shared):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    from ray_tpu.dag import InputNode
    with InputNode() as inp:
        dag = mul.bind(add.bind(inp, 10), 2)
    assert ray_tpu.get(dag.execute(5)) == 30
    assert ray_tpu.get(dag.execute(0)) == 20


def test_dag_shared_subgraph_runs_once(ray_start_shared):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def pair(a, b):
        return (a, b)

    c = Counter.remote()

    @ray_tpu.remote
    def bump_via(c):
        return ray_tpu.get(c.bump.remote())

    shared = bump_via.bind(c)
    dag = pair.bind(shared, shared)
    a, b = ray_tpu.get(dag.execute())
    # the shared node must execute once, both consumers see one value
    assert a == b == 1


def test_dag_actor_nodes(ray_start_shared):
    @ray_tpu.remote
    class Acc:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    from ray_tpu.dag import InputNode
    with InputNode() as inp:
        node = Acc.bind(100)
        dag = node.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 105


def test_workflow_run_and_resume(ray_start_shared, tmp_path):
    from ray_tpu import workflow
    workflow.set_storage(str(tmp_path))
    calls_file = tmp_path / "calls.txt"

    @ray_tpu.remote
    def record(x):
        with open(calls_file, "a") as f:
            f.write(f"{x}\n")
        return x * 2

    @ray_tpu.remote
    def combine(a, b):
        return a + b

    dag = combine.bind(record.bind(1), record.bind(2))
    out = workflow.run(dag, workflow_id="wf1")
    assert out == 6
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 6
    n_calls = len(calls_file.read_text().splitlines())
    assert n_calls == 2
    # resume: all steps checkpointed → no re-execution
    assert workflow.resume("wf1") == 6
    assert len(calls_file.read_text().splitlines()) == n_calls


def test_workflow_failure_then_resume(ray_start_shared, tmp_path):
    from ray_tpu import workflow
    workflow.set_storage(str(tmp_path))
    flag = tmp_path / "fail.flag"
    flag.write_text("1")
    side = tmp_path / "side.txt"

    @ray_tpu.remote
    def step_a():
        with open(side, "a") as f:
            f.write("a\n")
        return 10

    @ray_tpu.remote
    def step_b(a, flag_path):
        if os.path.exists(flag_path):
            raise RuntimeError("injected failure")
        return a + 1

    dag = step_b.bind(step_a.bind(), str(flag))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf2")
    assert workflow.get_status("wf2") == "FAILED"
    # step_a's checkpoint must survive the failure
    flag.unlink()
    out = workflow.resume("wf2")
    assert out == 11
    # step_a ran exactly once across both attempts
    assert side.read_text().splitlines() == ["a"]


def test_workflow_kwarg_steps_get_distinct_ids(ray_start_shared,
                                               tmp_path):
    from ray_tpu import workflow
    workflow.set_storage(str(tmp_path))

    @ray_tpu.remote
    def tag(x, mode="a"):
        return f"{x}-{mode}"

    @ray_tpu.remote
    def join(a, b):
        return (a, b)

    dag = join.bind(tag.bind(1, mode="a"), tag.bind(1, mode="b"))
    out = workflow.run(dag, workflow_id="wf-kw")
    # steps differing only in kwargs must NOT share a checkpoint
    assert out == ("1-a", "1-b")


def test_queue_no_thread_starvation(ray_start_shared):
    """Many blocked getters must not deadlock the queue actor
    (blocking is client-side polling, server calls are short)."""
    import threading
    from ray_tpu.util import Queue
    q = Queue()
    results = []

    def consumer():
        # generous timeout: 10 pollers share one client connection, and
        # under full-suite load a poll round-trip can take seconds
        results.append(q.get(timeout=120))

    threads = [threading.Thread(target=consumer) for _ in range(10)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    for i in range(10):
        q.put(i)
    for t in threads:
        t.join(timeout=150)
    assert not any(t.is_alive() for t in threads), "getters starved"
    assert sorted(results) == list(range(10))


def test_workflow_run_async(ray_start_shared, tmp_path):
    from ray_tpu import workflow
    workflow.set_storage(str(tmp_path))

    @ray_tpu.remote
    def fast(x):
        return x + 1

    ref = workflow.run_async(fast.bind(1), workflow_id="wf3")
    assert ray_tpu.get(ref, timeout=60) == 2
