"""Differential + invariant fuzz for the scheduling ledgers.

Drives PyLedger and NativeLedger through identical randomized op
sequences (submit / poll / release / bundle prepare-commit-cancel-
return / drain) and asserts, at every quiescent point:

  - CONSERVATION: after all queues drain and everything releases, the
    node pool returns to exactly the initial totals and chip set;
  - COMPLETION: every submitted task either dispatches or is doomed
    with its placement group — nothing is stranded;
  - SAFETY: availability never goes negative, never exceeds totals,
    chips are never double-granted, grants are never partial.

(Cross-ledger SCHEDULES are deliberately not compared: which class
wins contended resources at each poll is unspecified — see the
sched.py docstring — so two valid ledgers produce different dispatch
multisets for the same interleaved op sequence.)

This is the permanent form of the ad-hoc differential fuzzer used to
verify the schedcore port during review.
"""

import random

import pytest

from ray_tpu._private.sched import (NativeLedger, PendingTask, PyLedger,
                                    _lib)

TOTALS = {"CPU": 8.0, "TPU": 4.0, "memory": 1e9}
CHIPS = [0, 1, 2, 3]

DEMANDS = [
    {"CPU": 1.0},
    {"CPU": 0.5},
    {"CPU": 2.0, "TPU": 1},
    {"CPU": 1.0, "TPU": 2},
    {"CPU": 1.0 / 3.0},
    {"CPU": 0.5, "memory": 1e8},
]


def _pt(demand, pg=None):
    spec = {"resources": dict(demand), "task_id": "t"}
    if pg:
        spec["placement_group"] = pg
    return PendingTask(spec, None)


def _chips_outstanding(granted):
    return sorted(c for chips in granted.values() for c in chips)


def _drive(led, seed, steps=400):
    """One randomized session; returns the multiset of dispatched
    demands. Asserts safety invariants throughout."""
    rng = random.Random(seed)
    running = {}          # id(pt) -> (pt, chips)
    bundles = {}          # key -> state in {"prepared", "committed"}
    dispatched = []
    next_pg = 0

    for _ in range(steps):
        op = rng.random()
        if op < 0.35:  # submit a plain or bundle task
            if bundles and rng.random() < 0.4:
                key = rng.choice(list(bundles))
                pt = _pt(rng.choice(DEMANDS[:2]),
                         pg={"pg_id": key[0], "bundle_index": key[1]})
            else:
                pt = _pt(rng.choice(DEMANDS))
            led.append(pt)
        elif op < 0.60:  # poll + start whatever dispatches
            dispatches, blocked, more = led.poll()
            for pt, chips in dispatches:
                assert len(chips) == pt.tpu_demand  # full grant only
                running[id(pt)] = (pt, chips)
                dispatched.append(tuple(sorted(pt.demand.items())))
            out = _chips_outstanding(
                {k: v[1] for k, v in running.items()})
            assert len(out) == len(set(out)), "chip double-grant"
        elif op < 0.80 and running:  # finish a running task
            k = rng.choice(list(running))
            pt, chips = running.pop(k)
            led.release(pt, chips)
        elif op < 0.86:  # new bundle prepare
            key = (f"pg{next_pg}", 0)
            next_pg += 1
            if led.prepare_bundle(key, rng.choice(
                    [{"CPU": 1.0}, {"CPU": 2.0, "TPU": 1}])):
                bundles[key] = "prepared"
        elif op < 0.92 and bundles:  # advance a bundle's lifecycle
            key = rng.choice(list(bundles))
            if bundles[key] == "prepared":
                if rng.random() < 0.5:
                    assert led.commit_bundle(key)
                    bundles[key] = "committed"
                else:
                    led.cancel_bundle(key)
                    led.drain_pg(key[0])  # doom queued targeters
                    del bundles[key]
            else:
                led.return_bundle(key)
                for pt in led.drain_pg(key[0]):
                    pass  # doomed while queued: nothing was granted
                del bundles[key]
        # availability must never exceed totals or go negative
        for name, total in TOTALS.items():
            avail = led.avail_get(name)
            assert -1e-6 <= avail <= total + 1e-6, (name, avail)

    # quiesce: finish running tasks, return bundles, drain queues
    for pt, chips in list(running.values()):
        led.release(pt, chips)
    for key, state in list(bundles.items()):
        if state == "prepared":
            led.cancel_bundle(key)
        else:
            led.return_bundle(key)
        led.drain_pg(key[0])
    # with all bundles gone and resources free, every remaining queued
    # task is plain and must dispatch — nothing may be stranded
    while True:
        dispatches, blocked, more = led.poll()
        if not dispatches:
            break
        for pt, chips in dispatches:
            led.release(pt, chips)
            dispatched.append(tuple(sorted(pt.demand.items())))
    assert led.pending_tasks() == [], "stranded tasks after quiesce"
    return dispatched


@pytest.mark.parametrize("seed", range(12))
def test_conservation_completion_safety(seed):
    if _lib() is None:
        pytest.skip("native lib unavailable")
    for cls in (PyLedger, NativeLedger):
        led = cls(dict(TOTALS), list(CHIPS))
        _drive(led, seed)
        # conservation: the node pool is exactly restored
        for name, total in TOTALS.items():
            assert led.avail_get(name) == pytest.approx(total, abs=1e-3), \
                (cls.__name__, name, led.avail_get(name))
        assert led.node_chips_count() == len(CHIPS), cls.__name__
        assert led.pending_count() == 0, cls.__name__
