"""RLModule/Learner next-gen stack (reference: rllib/core/ —
rl_module.py, learner/learner.py, learner_group.py)."""

import numpy as np
import pytest

from ray_tpu.rllib.core import (DEFAULT_MODULE_ID, Learner, LearnerGroup,
                                MultiRLModule, PPOLearner, RLModule,
                                RLModuleSpec)
from ray_tpu.rllib.env import Box, Discrete


def _spec(seed=0):
    return RLModuleSpec(observation_space=Box(-1, 1, (4,)),
                        action_space=Discrete(2), seed=seed)


def _ppo_batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, (n,)).astype(np.int32),
        "action_logp": np.full((n,), -0.69, np.float32),
        "advantages": rng.standard_normal((n,)).astype(np.float32),
        "value_targets": rng.standard_normal((n,)).astype(np.float32),
    }


def test_rl_module_three_forwards():
    mod = _spec().build()
    batch = {"obs": np.zeros((8, 4), np.float32)}
    inf = mod.forward_inference(batch)
    assert inf["actions"].shape == (8,)
    assert inf["action_dist_inputs"].shape == (8, 2)
    exp = mod.forward_exploration(batch)
    assert exp["actions"].shape == (8,) and "action_logp" in exp
    # exploration on a fresh rng stream is stochastic across calls
    exp2 = mod.forward_exploration(
        {"obs": np.random.default_rng(0).standard_normal(
            (512, 4)).astype(np.float32)})
    assert len(set(exp2["actions"].tolist())) > 1
    tr = mod.forward_train(batch)
    assert set(tr) == {"action_dist_inputs", "vf_preds"}


def test_rl_module_spec_is_deterministic():
    a, b = _spec(seed=7).build(), _spec(seed=7).build()
    sa, sb = a.get_state(), b.get_state()
    import jax
    flat_a, flat_b = jax.tree.leaves(sa), jax.tree.leaves(sb)
    assert all(np.allclose(x, y) for x, y in zip(flat_a, flat_b))
    c = _spec(seed=8).build()
    assert not all(
        np.allclose(x, y) for x, y in
        zip(jax.tree.leaves(c.get_state()), flat_a))


def test_ppo_learner_update_reduces_loss():
    learner = PPOLearner(module_spec=_spec(), config={"lr": 5e-3})
    batch = _ppo_batch()
    first = learner.update_from_batch(batch)[DEFAULT_MODULE_ID]
    assert {"total_loss", "policy_loss", "vf_loss", "entropy",
            "grad_norm"} <= set(first)
    losses = [first["total_loss"]]
    for _ in range(30):
        losses.append(
            learner.update_from_batch(batch)[DEFAULT_MODULE_ID]
            ["total_loss"])
    assert losses[-1] < losses[0]


def test_multi_module_learner_updates_only_named_modules():
    learner = PPOLearner(module_specs={"a": _spec(1), "b": _spec(2)},
                         config={"lr": 1e-3})
    import jax
    b_before = jax.tree.leaves(learner.module["b"].get_state())
    out = learner.update_from_batch({"a": _ppo_batch()})
    assert set(out) == {"a"}
    b_after = jax.tree.leaves(learner.module["b"].get_state())
    assert all(np.allclose(x, y) for x, y in zip(b_before, b_after))


def test_learner_group_distributed_stays_synchronized(ray_start_shared):
    group = LearnerGroup(
        PPOLearner, num_learners=2,
        learner_kwargs={"module_spec": _spec(), "config": {"lr": 1e-3}})
    try:
        assert not group.is_local
        for i in range(3):
            group.update_from_batch(_ppo_batch(seed=i))
        # replicas applied identical averaged updates -> identical state
        import ray_tpu
        states = ray_tpu.get([w.get_state.remote()
                              for w in group._workers])
        import jax
        fa = jax.tree.leaves(states[0])
        fb = jax.tree.leaves(states[1])
        assert all(np.allclose(x, y, atol=1e-6) for x, y in zip(fa, fb))
    finally:
        group.shutdown()


def test_learner_group_local_mode():
    group = LearnerGroup(
        PPOLearner, num_learners=0,
        learner_kwargs={"module_spec": _spec(), "config": {"lr": 5e-3}})
    assert group.is_local
    out = group.update_from_batch(_ppo_batch())
    assert DEFAULT_MODULE_ID in out
    state = group.get_state()
    group2 = LearnerGroup(
        PPOLearner, num_learners=0,
        learner_kwargs={"module_spec": _spec(9), "config": {}})
    group2.set_state(state)
    import jax
    fa = jax.tree.leaves(state["module"])
    fb = jax.tree.leaves(group2.get_state()["module"])
    assert all(np.allclose(x, y) for x, y in zip(fa, fb))
