"""Placement group tests: TPU chip reservation + basic PG semantics.

Reference analogue: python/ray/tests/test_placement_group*.py; the chip
reservation semantics under test mirror how the reference converts bundle
resources into node-local resource *instances*
(placement_group_resource_manager.cc) so bundles own disjoint GPU/TPU sets.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util.placement_group import (
    placement_group, remove_placement_group)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@pytest.fixture(scope="function")
def tpu4_cluster():
    ctx = ray_tpu.init(num_cpus=4, num_tpus=4, ignore_reinit_error=True,
                       object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _chips_in_bundle(pg, bundle_index=0, num_tpus=1):
    @ray_tpu.remote(num_cpus=0.5, num_tpus=num_tpus,
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        pg, placement_group_bundle_index=bundle_index))
    def which_chips():
        return ray_tpu.get_tpu_ids()

    return which_chips


def test_two_tpu_bundles_get_disjoint_chips(tpu4_cluster):
    pg1 = placement_group([{"CPU": 1, "TPU": 2}])
    pg2 = placement_group([{"CPU": 1, "TPU": 2}])
    assert pg1.ready(timeout=30)
    assert pg2.ready(timeout=30)

    chips1 = ray_tpu.get(_chips_in_bundle(pg1, num_tpus=2).remote(),
                         timeout=60)
    chips2 = ray_tpu.get(_chips_in_bundle(pg2, num_tpus=2).remote(),
                         timeout=60)
    assert len(chips1) == 2 and len(chips2) == 2
    assert set(chips1).isdisjoint(set(chips2)), (chips1, chips2)
    assert set(chips1) | set(chips2) == {0, 1, 2, 3}

    remove_placement_group(pg1)
    remove_placement_group(pg2)


def test_non_pg_task_cannot_drain_bundle_chips(tpu4_cluster):
    # Bundle reserves every chip on the node; a non-PG TPU task must wait.
    pg = placement_group([{"CPU": 1, "TPU": 4}])
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=0.5, num_tpus=1)
    def wants_a_chip():
        return ray_tpu.get_tpu_ids()

    ref = wants_a_chip.remote()
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=3.0)
    assert not ready, "non-PG task stole a chip reserved by the bundle"

    # the bundle can still use all four reserved chips meanwhile
    chips = ray_tpu.get(_chips_in_bundle(pg, num_tpus=4).remote(),
                        timeout=60)
    assert sorted(chips) == [0, 1, 2, 3]

    # releasing the PG frees the chips and unblocks the waiting task
    remove_placement_group(pg)
    got = ray_tpu.get(ref, timeout=60)
    assert len(got) == 1


def test_sequential_pg_tasks_reuse_bundle_chips(tpu4_cluster):
    pg = placement_group([{"CPU": 1, "TPU": 2}])
    assert pg.ready(timeout=30)
    first = ray_tpu.get(_chips_in_bundle(pg, num_tpus=2).remote(),
                        timeout=60)
    second = ray_tpu.get(_chips_in_bundle(pg, num_tpus=2).remote(),
                         timeout=60)
    # chips return to the *bundle's* pool, not the node pool
    assert sorted(first) == sorted(second)
    remove_placement_group(pg)


def test_pg_actor_gets_bundle_chips(tpu4_cluster):
    pg = placement_group([{"CPU": 1, "TPU": 1}, {"CPU": 1, "TPU": 1}])
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=0.5, num_tpus=1)
    class ChipHolder:
        def chips(self):
            return ray_tpu.get_tpu_ids()

    a = ChipHolder.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=0)).remote()
    b = ChipHolder.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=1)).remote()
    ca = ray_tpu.get(a.chips.remote(), timeout=60)
    cb = ray_tpu.get(b.chips.remote(), timeout=60)
    assert len(ca) == 1 and len(cb) == 1
    assert set(ca).isdisjoint(set(cb))
    ray_tpu.kill(a)
    ray_tpu.kill(b)
    remove_placement_group(pg)


def test_removed_pg_returns_chips_to_node(tpu4_cluster):
    pg = placement_group([{"CPU": 1, "TPU": 4}])
    assert pg.ready(timeout=30)
    remove_placement_group(pg)

    @ray_tpu.remote(num_cpus=0.5, num_tpus=4)
    def all_chips():
        return ray_tpu.get_tpu_ids()

    chips = ray_tpu.get(all_chips.remote(), timeout=60)
    assert sorted(chips) == [0, 1, 2, 3]
