"""TPU chip/topology detection (raylet.py detect_tpu_chips).

Reference analogue: _private/resource_spec.py accelerator
autodetection tests.
"""

import os
from unittest import mock

from ray_tpu._private.raylet import (_chips_from_accel_type,
                                     detect_tpu_chips)
from ray_tpu.common.config import SystemConfig


def _cfg(chips=-1):
    c = SystemConfig()
    c.tpu_chips_per_host = chips
    return c


def test_explicit_config_wins():
    with mock.patch.dict(os.environ, {"RTPU_NUM_TPUS": "7"}):
        assert detect_tpu_chips(_cfg(chips=2)) == 2


def test_env_override():
    with mock.patch.dict(os.environ, {"RTPU_NUM_TPUS": "3"}):
        assert detect_tpu_chips(_cfg()) == 3


def test_granted_chips_env():
    env = {"TPU_VISIBLE_CHIPS": "0,1,2", "RTPU_NUM_TPUS": ""}
    env.pop("RTPU_NUM_TPUS")
    with mock.patch.dict(os.environ, env, clear=False):
        os.environ.pop("RTPU_NUM_TPUS", None)
        assert detect_tpu_chips(_cfg()) == 3
    # empty grant = zero chips (a worker fenced off from the TPU)
    with mock.patch.dict(os.environ, {"TPU_VISIBLE_CHIPS": ""}):
        os.environ.pop("RTPU_NUM_TPUS", None)
        assert detect_tpu_chips(_cfg()) == 0


def test_accel_type_parsing():
    # v5e counts chips directly
    assert _chips_from_accel_type("v5litepod-8") == 8
    # v4 counts cores (2 per chip); without TPU_WORKER_HOSTNAMES the
    # per-host physical ceiling (4 chips on v4) caps the guess so a
    # multi-host slice can't be mistaken for one 16-chip host
    assert _chips_from_accel_type("v4-32") == 4
    with mock.patch.dict(os.environ, {
            "TPU_WORKER_HOSTNAMES": "h0,h1,h2,h3"}):
        assert _chips_from_accel_type("v4-32") == 4
    assert _chips_from_accel_type("bogus") is None


def test_accel_type_divided_across_hosts():
    with mock.patch.dict(os.environ, {
            "TPU_WORKER_HOSTNAMES": "host-0,host-1"}):
        assert _chips_from_accel_type("v5litepod-16") == 8


def test_accel_type_env_fallback():
    env = {"TPU_ACCELERATOR_TYPE": "v5litepod-4",
           "TPU_SKIP_MDS_QUERY": "1"}
    with mock.patch.dict(os.environ, env):
        for k in ("RTPU_NUM_TPUS", "TPU_VISIBLE_CHIPS",
                  "TPU_VISIBLE_DEVICES"):
            os.environ.pop(k, None)
        with mock.patch("os.path.isdir", return_value=False):
            assert detect_tpu_chips(_cfg()) == 4
