"""Object spilling tests: store overcommit spills primaries to disk and
restores them on get.

Reference analogue: python/ray/tests/test_object_spilling.py over
local_object_manager.h SpillObjects + _private/external_storage.py
(filesystem backend).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="function")
def small_store_cluster():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                       object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_put_2x_capacity_and_get_everything_back(small_store_cluster):
    # 16 x 8 MiB = 128 MiB of objects through a 64 MiB store.
    n, size = 16, 8 * 1024 * 1024
    rng = np.random.default_rng(0)
    arrays = [rng.integers(0, 256, size, dtype=np.uint8) for _ in range(n)]
    sums = [int(a.sum()) for a in arrays]
    refs = [ray_tpu.put(a) for a in arrays]
    del arrays

    # everything restorable, in any order (reverse hits spilled ones first)
    for i in reversed(range(n)):
        value = ray_tpu.get(refs[i], timeout=60)
        assert value.nbytes == size
        assert int(value.sum()) == sums[i]
        del value  # drop the zero-copy view so the slot can respill


def test_spilled_objects_visible_to_tasks(small_store_cluster):
    n, size = 12, 8 * 1024 * 1024
    refs = [ray_tpu.put(np.full(size, i % 251, dtype=np.uint8))
            for i in range(n)]

    @ray_tpu.remote(num_cpus=1)
    def checksum(a, expect):
        return bool((a == expect).all())

    # tasks consume the oldest (certainly spilled) objects as plasma deps
    oks = ray_tpu.get([checksum.remote(refs[i], i % 251) for i in range(4)],
                      timeout=120)
    assert all(oks)


def test_spill_metrics_reported(small_store_cluster):
    n, size = 12, 8 * 1024 * 1024
    refs = [ray_tpu.put(np.zeros(size, dtype=np.uint8)) for i in range(n)]
    nodes = ray_tpu.nodes()
    spilled = sum(nd.get("num_spilled_objects", 0) for nd in nodes
                  if "num_spilled_objects" in nd)
    # at least (total - capacity) worth of objects must have been spilled
    if not spilled:
        # node table may not carry store info; ask the raylet directly
        w = ray_tpu._private.worker.global_worker()
        info = w.call_sync(w.raylet, "get_info", {})
        spilled = info["num_spilled_objects"]
    assert spilled >= 4
    del refs
