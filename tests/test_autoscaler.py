"""Autoscaler tests on an isolated multi-raylet cluster
(reference: tests/test_autoscaler_fake_multinode.py)."""

import time

import ray_tpu


def test_autoscaler_scale_up_and_down(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.connect()
    from ray_tpu.autoscaler import (FakeMultiNodeProvider,
                                   StandardAutoscaler,
                                   request_resources)
    w = ray_tpu._worker_mod.global_worker()

    def gcs_call(method, payload):
        return w.call_sync(w.gcs, method, payload, timeout=30)

    provider = FakeMultiNodeProvider({
        "session_dir": cluster.session_dir,
        "gcs_address": cluster.gcs_address})
    autoscaler = StandardAutoscaler(
        provider,
        {"worker": {"resources": {"CPU": 2}, "min_workers": 0,
                    "max_workers": 3}},
        gcs_call, idle_timeout_s=1.0)
    # no demand → nothing happens
    r = autoscaler.update()
    assert r["launched"] == [] and r["terminated"] == []
    # demand for 4 CPUs beyond the 2-CPU head → 2 new worker nodes
    request_resources([{"CPU": 2}, {"CPU": 2}, {"CPU": 2}])
    r = autoscaler.update()
    assert len(r["launched"]) >= 1
    cluster.wait_for_nodes()
    assert len(provider.non_terminated_nodes()) >= 1
    # drop demand → idle nodes reaped after the timeout
    request_resources([])
    time.sleep(1.5)
    r = autoscaler.update()
    # one more tick so idle_since crosses the threshold for all
    time.sleep(1.5)
    r2 = autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 0 or \
        (r["terminated"] or r2["terminated"])




def test_autoscaler_no_relaunch_while_pending():
    """Launched-but-unregistered nodes count as capacity, so the same
    unmet bundle doesn't trigger a launch every tick (reference:
    pending-launch tracking in autoscaler.py)."""
    from ray_tpu.autoscaler import NodeProvider, StandardAutoscaler
    import json as _json

    class SlowBootProvider(NodeProvider):
        def __init__(self):
            super().__init__({})
            self.created = []

        def non_terminated_nodes(self):
            return list(self.created)

        def create_node(self, node_config, count):
            ids = [f"slow-{len(self.created) + i}" for i in range(count)]
            self.created += ids
            return ids  # never registers in the GCS snapshot

        def terminate_node(self, node_id):
            self.created.remove(node_id)

    demand = [{"CPU": 2}]

    def gcs_call(method, payload):
        if method == "get_nodes":
            return []  # booting nodes never register
        if method == "kv_get":
            return {"value": _json.dumps(demand).encode()}
        return {}

    a = StandardAutoscaler(
        SlowBootProvider(),
        {"worker": {"resources": {"CPU": 2}, "min_workers": 0,
                    "max_workers": 10}},
        gcs_call, idle_timeout_s=60.0)
    r1 = a.update()
    assert len(r1["launched"]) == 1
    # same demand, node still booting -> NO new launch
    r2 = a.update()
    assert r2["launched"] == []
    r3 = a.update()
    assert r3["launched"] == []
