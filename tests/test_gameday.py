"""Game-day SLO harness: deterministic open-loop load generation,
client-side SLO accounting, replayable composed scenarios, request-id
propagation (proxy→router→replica + ledger echo), and the flagship
tier-1 gate — rolling update + chaos-seeded controller kill under peak
open-loop load with ZERO client-observed failed requests and an exact
client/server reconciliation (docs/GAMEDAY.md; ROADMAP item 8).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.gameday import loadgen, scenario, slo
from ray_tpu.gameday.reconcile import reconcile as run_reconcile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ pure units


def test_arrival_schedule_deterministic_and_seed_sensitive():
    """Same (spec, seed) -> byte-identical arrivals, ids included;
    a different seed is a different game day."""
    sc = scenario.load_scenario("flagship")
    a = [x.to_dict() for x in sc.arrival_schedule().arrivals]
    b = [x.to_dict() for x in
         scenario.load_scenario("flagship").arrival_schedule().arrivals]
    assert a == b and len(a) > 100
    c = [x.to_dict() for x in
         scenario.load_scenario("flagship",
                                seed=999).arrival_schedule().arrivals]
    assert c != a
    # ids embed the seed so two seeds can never alias in a ledger
    assert a[0]["rid"].startswith("flagship-411-")
    assert c[0]["rid"].startswith("flagship-999-")


def test_arrival_shapes():
    """The generator actually produces the advertised shapes: flash
    crowd bursts, diurnal crest, heavy-tail sizes, tenant skew."""
    sched = loadgen.build_schedule(
        [{"name": "fc", "duration_s": 8.0, "shape": "flash_crowd",
          "base_rps": 30, "burst_rps": 120, "burst_start_frac": 0.25,
          "burst_frac": 0.5}], seed=5)
    base = sched.rate_in(0.0, 2.0)
    burst = sched.rate_in(2.0, 6.0)
    assert burst > 2.5 * base, (base, burst)

    sched = loadgen.build_schedule(
        [{"name": "d", "duration_s": 10.0, "shape": "diurnal",
          "min_rps": 10, "peak_rps": 100}], seed=6)
    trough = (sched.rate_in(0.0, 1.0) + sched.rate_in(9.0, 10.0)) / 2
    crest = sched.rate_in(4.0, 6.0)
    assert crest > 2.0 * trough, (trough, crest)

    sched = loadgen.build_schedule(
        [{"name": "s", "duration_s": 20.0, "shape": "steady",
          "rps": 100}], seed=7, tenants=4, tenant_skew=1.2)
    sizes = sorted(a.size for a in sched.arrivals)
    median = sizes[len(sizes) // 2]
    assert sizes[-1] > 5 * median, "sizes are not heavy-tailed"
    by_tenant = {}
    for a in sched.arrivals:
        by_tenant[a.tenant] = by_tenant.get(a.tenant, 0) + 1
    shares = sorted(by_tenant.values(), reverse=True)
    assert shares[0] > 1.8 * shares[-1], f"no tenant skew: {by_tenant}"


def test_histogram_quantiles_close_to_exact():
    import random
    np = pytest.importorskip("numpy")
    rng = random.Random(3)
    vals = [rng.lognormvariate(-4, 1.0) for _ in range(5000)]
    h = slo.LatencyHistogram()
    for v in vals:
        h.record(v)
    for q in (0.5, 0.99, 0.999):
        got = h.quantile(q)
        want = float(np.percentile(vals, q * 100))
        # log buckets grow 2.5%/step; the conservative upper edge may
        # sit one bucket above the exact sample
        assert want <= got <= want * 1.06, (q, got, want)
    assert h.quantile(0.999) <= h.max_s


def test_error_budget_burn_math():
    # 99.9% over 1000 requests: the budget is exactly one failure
    assert slo.error_budget_burn(1000, 0, 0.999) == 0.0
    assert slo.error_budget_burn(1000, 1, 0.999) == pytest.approx(1.0)
    assert slo.error_budget_burn(1000, 3, 0.999) == pytest.approx(3.0)
    # a zero-failure SLO has no budget: any failure burns infinitely
    assert slo.error_budget_burn(10, 1, 1.0) == float("inf")


def test_scenario_replayable_and_json_roundtrip(tmp_path):
    """Same seed -> same chaos schedule AND same arrivals, including
    through a JSON spec file round-trip: the replay property the
    flagship acceptance criterion leans on."""
    sc = scenario.load_scenario("flagship")
    cc1 = scenario.chaos_config(sc)
    cc2 = scenario.chaos_config(scenario.load_scenario("flagship"))
    assert cc1 == cc2
    assert cc1["schedule"], "flagship must schedule a controller kill"

    path = tmp_path / "spec.json"
    path.write_text(json.dumps(sc.to_dict()))
    sc2 = scenario.load_scenario(str(path))
    assert scenario.chaos_config(sc2) == cc1
    assert [a.to_dict() for a in sc2.arrival_schedule().arrivals] == \
        [a.to_dict() for a in sc.arrival_schedule().arrivals]
    # scale stretches phase durations and stays deterministic
    half = sc.arrival_schedule(0.5)
    assert half.duration_s == pytest.approx(
        sc.arrival_schedule(1.0).duration_s / 2)
    assert [a.to_dict() for a in half.arrivals] == \
        [a.to_dict() for a in sc.arrival_schedule(0.5).arrivals]
    assert len(half.arrivals) > 50


def test_open_loop_charges_stall_to_scheduled_arrivals():
    """The anti-coordinated-omission property: with one worker wedged
    behind a slow request, arrivals scheduled during the stall report
    the queueing delay a real user would have seen — not the healthy
    service time of whenever they finally got sent."""
    arrivals = [loadgen.Arrival(i * 0.02, f"r{i}", "p", "t", 1.0)
                for i in range(5)]
    sched = loadgen.ArrivalSchedule(
        arrivals, [{"name": "p", "duration_s": 0.1}], seed=0)

    def send(_a):
        time.sleep(0.15)

    lg = loadgen.OpenLoopRunner(sched, send, max_workers=1)
    records = sorted(lg.run(), key=lambda r: r.rid)
    assert all(r.outcome == "ok" for r in records)
    # worker serializes 5 x 150 ms; the last arrival (scheduled t=80ms)
    # completes ~t=750ms => open-loop latency ~670ms >> its 150 ms
    # service time
    assert records[-1].latency_s > 0.4, records[-1].latency_s
    assert records[-1].service_s < 0.3
    # the first request saw no queue: latency ~ service time
    assert records[0].latency_s < 0.3


def test_reconcile_detects_each_mismatch_class():
    sc = scenario.load_scenario("flagship")
    client = {"ok": ["a", "b"], "shed": ["c"], "failed": []}
    view = {
        "replica_ledgers": [
            {"deployment": "GameDay", "replica": "R1", "live": True,
             "records": [["a", "ok", 0.01], ["c", "shed", 0.0]]},
            {"deployment": "GameDay", "replica": "R2", "live": False,
             "records": [["b", "ok", 0.02]]}],
        "replica_metrics": {"R1": {"total_requests": 1,
                                   "total_shed": 1}},
        "serve_metrics": {"GameDay": {"requests_total": 1,
                                      "shed_total": 1}},
        "task_delta": {"finished": 2, "failed": 1, "dropped": 0,
                       "events_dropped": 0},
        "prometheus": {"serve": {"GameDay": {"requests_total": 1,
                                             "shed_total": 1}}},
        "chaos_fired": [{"site": "serve.controller.tick", "op": "kill",
                         "n": 6}],
        "chaos_expected": scenario.chaos_config(sc),
    }
    assert run_reconcile(sc, client, view)["ok"]

    def run(mutate):
        import copy
        v = copy.deepcopy(view)
        c = {k: list(vs) for k, vs in client.items()}
        mutate(c, v)
        return {chk["name"]: chk["ok"] for chk in
                run_reconcile(sc, c, v)["checks"]}

    # a client success the server never completed
    checks = run(lambda c, v: c["ok"].append("ghost"))
    assert not checks["completed-join"]
    # a server completion the client saw fail (unexplained outcome)
    checks = run(lambda c, v: (c["ok"].remove("b"),
                               c["failed"].append("b")))
    assert not checks["admitted-equals-completed"]
    # a shed the server never listed
    checks = run(lambda c, v: c["shed"].append("ghost-shed"))
    assert not checks["shed-listed"]
    # replica counters drifting from the replica's own ledger
    checks = run(lambda c, v: v["replica_metrics"]["R1"].update(
        total_requests=99))
    assert not checks["replica-totals"]
    # controller aggregation disagreeing with replica counters
    checks = run(lambda c, v: v["serve_metrics"]["GameDay"].update(
        requests_total=99))
    assert not checks["serve-metrics-agree"]
    # the state engine counting a different story
    checks = run(lambda c, v: v["task_delta"].update(finished=99))
    assert not checks["state-engine-tasks"]
    # Prometheus exporting something else
    checks = run(lambda c, v: v["prometheus"]["serve"]["GameDay"].update(
        requests_total=99))
    assert not checks["prometheus-serve-gauges"]
    # a fault that fired off-schedule
    checks = run(lambda c, v: v["chaos_fired"].append(
        {"site": "serve.replica.request", "op": "kill", "n": 3}))
    assert not checks["chaos-schedule-replay"]
    # a lossy task table downgrades to skip, not to a false failure
    checks = run(lambda c, v: v["task_delta"].update(finished=99,
                                                     dropped=5))
    assert checks["state-engine-tasks"]


# -------------------------------------------- request-id plumbing (e2e)


@pytest.fixture(scope="module")
def rid_cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    # the flagship test may already have torn this cluster down (it
    # must own a fresh one for the chaos env) — teardown is best-effort
    try:
        from ray_tpu import serve
        serve.shutdown()
    except Exception:
        pass
    try:
        ray_tpu.shutdown()
    except Exception:
        pass


def _request_logs():
    """All live replica request ledgers, via the route table."""
    from ray_tpu.actor import get_actor_by_id
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    _, table = ray_tpu.get(ctrl.get_route_table.remote(), timeout=10.0)
    logs = []
    for info in table.values():
        for hex_id in info["replicas"]:
            h = get_actor_by_id(hex_id)
            logs.append(ray_tpu.get(h.get_request_log.remote(),
                                    timeout=10.0))
    return logs


def test_request_id_handle_path_lands_in_ledger(rid_cluster):
    """A handle caller tags a request with __rtpu_request_id__: user
    code must never see the kwarg, and the replica ledger must record
    (id, ok, latency)."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, name="Rid")
    def echo(payload=None, **kwargs):
        # the reserved kwarg must have been stripped
        assert "__rtpu_request_id__" not in kwargs, kwargs
        return {"got": payload}

    h = serve.run(echo.options(name="Rid").bind(), http_port=None)
    out = ray_tpu.get(h.remote({"x": 1}, __rtpu_request_id__="req-abc"),
                      timeout=30.0)
    assert out == {"got": {"x": 1}}

    logs = _request_logs()
    assert logs and logs[0]["deployment"] == "Rid"
    assert logs[0]["replica"].startswith("SERVE_REPLICA::Rid#")
    entries = {rid: (outcome, lat)
               for rid, outcome, lat in logs[0]["records"]}
    assert "req-abc" in entries, entries
    outcome, lat = entries["req-abc"]
    assert outcome == "ok" and lat >= 0.0
    assert not logs[0]["truncated"]


def test_request_id_http_header_roundtrip(rid_cluster):
    """X-Request-Id propagates proxy -> router -> replica (ledger
    entry) and is echoed on the response."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, name="RidHttp")
    def echo(payload=None):
        return {"ok": True}

    serve.run(echo.options(name="RidHttp").bind(),
              route_prefix="/rid", http_port=8341)
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote(), timeout=10.0)

    req = urllib.request.Request(f"http://127.0.0.1:{port}/rid",
                                 headers={"X-Request-Id": "http-42"})
    resp = urllib.request.urlopen(req, timeout=30)
    assert json.loads(resp.read()) == {"ok": True}
    assert resp.headers.get("X-Request-Id") == "http-42"

    rids = [rid for log in _request_logs()
            for rid, _o, _l in log["records"]]
    assert "http-42" in rids, rids


# ---------------------------------------------------- flagship (tier-1)


def _run_flagship(scale):
    from ray_tpu.gameday import load_scenario, run_scenario
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    sc = load_scenario("flagship")
    return sc, run_scenario(sc, scale=scale, dashboard_port=18472)


def test_flagship_gameday_zero_failed_and_exact_reconcile():
    """THE acceptance gate (ISSUE 11): a rolling update AND a
    chaos-seeded controller SIGKILL land during peak open-loop load;
    the game day passes only if no client-observed request failed, the
    client ledger reconciles exactly with the state engine / replica
    ledgers / Prometheus, and the fired faults match the seeded
    schedule."""
    sc, result = _run_flagship(scale=0.5)
    rep = result.report

    # zero client-observed failures through the whole composed scenario
    assert rep["overall"]["failed"] == 0, \
        [r.error for r in result.records if r.outcome == "failed"][:5]
    assert rep["overall"]["admitted"] > 100
    assert not rep["action_errors"], rep["action_errors"]

    # the faults really fired, per the seeded schedule
    fired = rep["chaos_fired"]
    assert any(f["site"] == "serve.controller.tick" for f in fired), \
        "controller kill never fired"

    # outside-in: every reconciliation check green
    recon = rep["reconciliation"]
    assert recon["ok"], [c for c in recon["checks"] if not c["ok"]]
    assert recon["counts"]["client_ok"] == rep["overall"]["admitted"]

    # the SLO verdict and its export round-trip
    assert rep["passed"], rep["slo"]
    assert rep.get("slo_gauges_published"), \
        "ray_tpu_slo_* gauges missing from /metrics after publish"

    # replay property: rebuilding the scenario reproduces the exact
    # fault schedule and arrival ids the run used
    from ray_tpu.gameday import load_scenario
    again = load_scenario("flagship")
    assert scenario.chaos_config(again) == \
        result.server_view["chaos_expected"]
    assert [a.rid for a in again.arrival_schedule(0.5).arrivals] == \
        [r.rid for r in sorted(result.records, key=lambda r: r.sched_t)]


def test_bench_gameday_smoke():
    """`_BENCH_GAMEDAY=1 python bench.py` runs a scenario end to end
    and emits the PERF.md row (flash-crowd: cheapest builtin, no
    controller restarts)."""
    env = dict(os.environ, _BENCH_GAMEDAY="1", JAX_PLATFORMS="cpu",
               BENCH_GAMEDAY_SCENARIOS="flash-crowd",
               BENCH_GAMEDAY_SCALE="0.5")
    env.pop("LIBTPU_INIT_ARGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        stdout=subprocess.PIPE, text=True, timeout=300, env=env,
        cwd=REPO_ROOT)
    row = None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            row = json.loads(line)
            break
    assert row is not None, proc.stdout
    assert row.get("metric") == "gameday", row
    fc = row["scenarios"]["flash-crowd"]
    for key in ("requests", "admitted", "shed", "failed", "p99_ms",
                "p999_ms", "availability_burn", "reconciled", "passed"):
        assert key in fc, (key, fc)
    assert fc["failed"] == 0, fc
    assert fc["reconciled"], fc


# ------------------------------------------------------------- slow soak


@pytest.mark.slow
def test_diurnal_soak_gameday():
    """Three diurnal cycles with two rolling updates and a controller
    kill — the long-haul version of the flagship gate."""
    from ray_tpu.gameday import load_scenario, run_scenario
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    sc = load_scenario("diurnal-soak")
    result = run_scenario(sc, scale=1.0, dashboard_port=18473)
    rep = result.report
    assert rep["overall"]["failed"] == 0
    assert rep["reconciliation"]["ok"], \
        [c for c in rep["reconciliation"]["checks"] if not c["ok"]]
    assert rep["passed"], rep["slo"]


@pytest.mark.slow
def test_replica_storm_gameday_bounded_blast_radius():
    """A replica SIGKILL mid-load: failures stay inside the scenario's
    budget and reconciliation (with lost-ledger tolerance) holds."""
    from ray_tpu.gameday import load_scenario, run_scenario
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    sc = load_scenario("replica-storm")
    result = run_scenario(sc, scale=1.0, dashboard_port=18474)
    rep = result.report
    fired = rep["chaos_fired"]
    assert any(f["site"] == "serve.replica.request" for f in fired)
    burn = rep["slo"]["availability_burn"]
    assert 0.0 <= burn <= 1.0, rep["overall"]
    assert rep["reconciliation"]["ok"], \
        [c for c in rep["reconciliation"]["checks"] if not c["ok"]]
