"""C++ client API: compile with g++ and drive a live cluster end to end.

Reference analogue: cpp/src/ray/test/cluster/cluster_mode_test.cc — a
non-Python driver performing put/get, named cross-language invocation,
error propagation, KV, and cluster info over the wire protocol.
"""

import os
import shutil
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVER_SCRIPT = """
import os, time
os.environ.setdefault("RTPU_PRESTART_WORKERS", "0")
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
from ray_tpu.util.client.server import ClientServer
from ray_tpu.util import cross_language

ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
cross_language.register_function("math.add", lambda a, b: a + b)
cross_language.register_function("str.concat", lambda a, b: a + b)

def boom():
    raise ValueError("kaboom")

cross_language.register_function("math.boom", boom)
srv = ClientServer(port=0, host="127.0.0.1")
print(f"PORT={srv.port}", flush=True)
while True:
    time.sleep(1)
"""


@pytest.fixture(scope="module")
def cpp_binary(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = tmp_path_factory.mktemp("cpp") / "smoke"
    src = os.path.join(REPO, "src", "cpp_client", "smoke_main.cc")
    subprocess.run(
        [gxx, "-std=c++17", "-O2", "-Wall", "-Werror", "-o", str(out),
         src, "-I", os.path.join(REPO, "src", "cpp_client")],
        check=True)
    return str(out)


@pytest.fixture(scope="module")
def server_port():
    env = dict(os.environ)
    env.pop("RTPU_ADDRESS", None)
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", SERVER_SCRIPT],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO)
    port = None
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
    if port is None:
        proc.kill()
        pytest.fail("client server did not start")
    yield port
    proc.kill()
    proc.wait(timeout=30)


def test_cpp_client_end_to_end(cpp_binary, server_port):
    r = subprocess.run([cpp_binary, str(server_port)],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout!r} stderr={r.stderr!r}"
    assert "CPP_CLIENT_OK" in r.stdout
