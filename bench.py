"""Flagship benchmark: ResNet-50 synthetic-data training throughput,
driven END-TO-END through the framework (ray_tpu.init → DataParallelTrainer
→ TPU worker → session.get_dataset_shard → double-buffered device feed).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Metric: ResNet-50 images/sec/chip, bf16, synthetic ImageNet shapes —
the reference's headline Train benchmark (reference:
release/air_tests/air_benchmarks/mlperf-train/resnet50_ray_air.py:194-196,
torchvision resnet50 under TorchTrainer/DDP). Baseline: 2500 images/s per
A100. The headline number is measured INSIDE a framework-managed train
worker; a raw-JAX control run (same step function, no framework) runs
first in its own subprocess so the orchestration overhead is visible as
`raw_img_per_sec` vs the headline.

A second model row rides in the same JSON line: GPT-2 small (the
flagship `entry()` model) train-step tokens/s/chip + MFU, measured in the
same framework-managed worker (`gpt2_*` keys).

Robustness:
  - the TPU is touched only by short-lived subprocesses (raw control, and
    the framework's TPU worker); the driver itself stays on CPU so libtpu
    is never double-claimed;
  - the supervisor retries a hung/failed attempt and falls back to a
    labeled CPU run; it always emits the ONE JSON line. The CPU fallback
    forces the platform via BOTH the env var and the live jax config —
    on this box the env var alone does not stop the tunneled TPU backend
    from initializing (the round-3 failure: all attempts, including the
    "CPU" one, wedged at TPU backend init);
  - subprocesses run in their own session; a timed-out attempt gets its
    whole process group SIGKILLed and reaped, so a wedged PJRT client
    can't hold the tunnel across attempts;
  - timing takes the best of several windows — the tunneled chip shows
    run-to-run noise from neighbors.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0  # A100 MLPerf-class ResNet-50 DDP

METRIC = "resnet50_images_per_sec_per_chip"
UNIT = "images/s/chip"

_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# Round-4 re-measurement: the scoped-vmem override round 3 added
# (--xla_tpu_scoped_vmem_limit_kib=98304) is a 5% REGRESSION on this
# chip (111.2ms vs 105.7ms/step raw control, back-to-back) — the
# compiler's default vmem budget wins, so every path strips
# LIBTPU_INIT_ARGS from its subprocess env.

READY_MARKER = "#BENCH_BACKEND_READY"
INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT", 300))
RUN_TIMEOUT_S = float(os.environ.get("BENCH_RUN_TIMEOUT", 2400))
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", 3))


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def _force_cpu_platform():
    """Pin jax to CPU before any backend init. BOTH knobs are required:
    on this box the tunneled TPU backend still initializes when only the
    env var is set (round-3 bench postmortem)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    for var in ("LIBTPU_INIT_ARGS", "TPU_LIBRARY_PATH"):
        os.environ.pop(var, None)
    import jax
    jax.config.update("jax_platforms", "cpu")


def _kill_group(proc):
    """SIGKILL a subprocess's whole session and reap it — a wedged PJRT
    client must not survive the attempt and hold the tunnel."""
    import signal
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        proc.kill()
    try:
        proc.wait(timeout=10)
    except Exception:
        pass


def _reap_framework_orphans():
    """Kill leftover ray_tpu node processes (gcs/raylet/workers). The
    framework driver spawns them with start_new_session=True, so killing
    the driver's group does NOT reach them — after a timed-out framework
    attempt the wedged train worker would keep holding the PJRT tunnel.
    The bench owns this box, so a cmdline sweep is safe."""
    import signal
    me = os.getpid()
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) == me:
            continue
        try:
            with open(f"/proc/{pid_s}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(errors="ignore")
        except OSError:
            continue
        if "ray_tpu._private" in cmd or "ray_tpu/_private" in cmd:
            try:
                os.kill(int(pid_s), signal.SIGKILL)
            except OSError:
                pass


def _emit(value, vs_baseline, **extras):
    line = {"metric": METRIC, "value": value, "unit": UNIT,
            "vs_baseline": vs_baseline}
    line.update(extras)
    print(json.dumps(line), flush=True)


# --------------------------------------------------------------- train body

def bench_loop(on_tpu: bool, make_feed=None):
    """The measured training loop. Runs inside the raw-control subprocess
    AND inside the framework train worker — identical math either way.

    Returns a dict of measurements. `make_feed(trainer, batch_size)`:
    optional factory returning an endless iterator of device-committed
    batches (the framework path feeds uint8 batches through the Dataset
    pipeline with double-buffered device_put); None = one resident batch
    (raw control — no input cost, the pure-compute ceiling).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.resnet import create_resnet
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import make_image_classifier_trainer, put_batch

    devices = jax.devices()
    n_dev = jax.local_device_count()
    if on_tpu:
        batch = int(os.environ.get("BENCH_BATCH", 256)) * n_dev
        image_size, dtype = 224, jnp.bfloat16
        # best-of-8 windows: the tunneled chip shows multi-percent
        # run-to-run noise from neighbors; more windows catch more of
        # the quiet ones (measured spread 101.7-111ms across runs)
        windows, steps_per_window, warmup = 8, 10, 3
    else:
        batch = 8 * n_dev
        image_size, dtype = 32, jnp.float32
        windows, steps_per_window, warmup = 1, 3, 1

    spec = MeshSpec(dp=n_dev)
    mesh = spec.build(devices[:n_dev])
    model = create_resnet("resnet50", num_classes=1000, dtype=dtype)
    trainer = make_image_classifier_trainer(
        model, mesh=mesh, spec=spec,
        input_shape=(1, image_size, image_size, 3))
    state = trainer.init(jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    feed = None
    if make_feed is not None:
        feed = make_feed(trainer, batch)
        resident = next(feed)  # template for compile (uint8 pipeline)
    else:
        images = rng.standard_normal(
            (batch, image_size, image_size, 3), dtype=np.float32)
        labels = rng.integers(0, 1000, (batch,), dtype=np.int32)
        resident = put_batch(trainer, {"image": images, "label": labels})

    t0 = time.perf_counter()
    try:
        step = trainer.step.lower(state, resident).compile()
        compile_s = time.perf_counter() - t0
        ca = step.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        step, compile_s, flops = trainer.step, time.perf_counter() - t0, None

    def next_batch():
        if feed is None:
            return resident
        return next(feed)

    # NB: sync via device_get of the loss (serial state dependency), not
    # block_until_ready — the latter does not reliably block through the
    # tunneled TPU platform here.
    for _ in range(warmup):
        state, metrics = step(state, next_batch())
    float(jax.device_get(metrics["loss"]))

    best_dt = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps_per_window):
            state, metrics = step(state, next_batch())
        float(jax.device_get(metrics["loss"]))
        dt = (time.perf_counter() - t0) / steps_per_window
        best_dt = dt if best_dt is None else min(best_dt, dt)

    out = {
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "n_chips": n_dev,
        "batch_per_chip": batch // n_dev,
        "step_time_ms": round(best_dt * 1e3, 2),
        "compile_s": round(compile_s, 2),
        "img_per_sec": round(batch / best_dt, 2),
        "img_per_sec_per_chip": round(batch / best_dt / n_dev, 2),
    }
    if flops:
        out["flops_per_step"] = flops
        peak = _peak_flops(devices[0].device_kind)
        if peak:
            # cost_analysis reports the per-device post-partition module,
            # so per-device flops over per-chip peak IS per-chip MFU
            out["mfu"] = round(flops / best_dt / peak, 4)
            out["peak_bf16_flops_per_chip"] = peak
    return out


def gpt2_loop(on_tpu: bool):
    """GPT-2 small train-step throughput (tokens/s/chip + MFU) — the
    flagship `entry()` model, measured as one donated pjit'd step with a
    device-resident batch. Reference analogue: the HF GPT-2 fine-tune
    config in BASELINE.md (train/huggingface/huggingface_trainer.py:157)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.gpt2 import GPT2Config
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import make_causal_lm_trainer, put_batch

    devices = jax.devices()
    n_dev = jax.local_device_count()
    if on_tpu:
        cfg = GPT2Config(vocab_size=50257, n_positions=1024, n_embd=768,
                         n_layer=12, n_head=12,
                         attention_backend="flash", dtype=jnp.bfloat16)
        batch = int(os.environ.get("BENCH_GPT2_BATCH", 16)) * n_dev
        seq = 1024
        windows, steps_per_window, warmup = 6, 5, 2
    else:
        cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=64,
                         n_layer=2, n_head=4,
                         attention_backend="reference", dtype=jnp.float32)
        batch, seq = 2 * n_dev, 32
        windows, steps_per_window, warmup = 1, 2, 1

    spec = MeshSpec(dp=n_dev)
    mesh = spec.build(devices[:n_dev])
    trainer = make_causal_lm_trainer(cfg, mesh=mesh, spec=spec)
    state = trainer.init(jax.random.PRNGKey(0))
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    resident = put_batch(trainer, {"input_ids": tokens, "labels": tokens})

    t0 = time.perf_counter()
    try:
        step = trainer.step.lower(state, resident).compile()
        compile_s = time.perf_counter() - t0
        ca = step.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        step, compile_s, flops = trainer.step, time.perf_counter() - t0, None

    for _ in range(warmup):
        state, metrics = step(state, resident)
    float(jax.device_get(metrics["loss"]))

    best_dt = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps_per_window):
            state, metrics = step(state, resident)
        float(jax.device_get(metrics["loss"]))
        dt = (time.perf_counter() - t0) / steps_per_window
        best_dt = dt if best_dt is None else min(best_dt, dt)

    out = {
        "gpt2_batch_per_chip": batch // n_dev,
        "gpt2_seq_len": seq,
        "gpt2_step_time_ms": round(best_dt * 1e3, 2),
        "gpt2_compile_s": round(compile_s, 2),
        "gpt2_tokens_per_sec_per_chip": round(
            batch * seq / best_dt / n_dev, 1),
    }
    if flops:
        peak = _peak_flops(devices[0].device_kind)
        if peak:
            # per-device flops (post-partition module) over per-chip peak
            out["gpt2_mfu"] = round(flops / best_dt / peak, 4)
    return out


# ----------------------------------------------------- raw control (subproc)

def _raw_main():
    """Raw-JAX control run: same loop, no framework. Own process so the
    chip is released before the framework worker claims it."""
    if os.environ.get("_BENCH_FORCE_CPU"):
        _force_cpu_platform()
    import jax
    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    print(f"{READY_MARKER} platform={devices[0].platform}", flush=True)
    print(json.dumps(bench_loop(on_tpu)), flush=True)


def _run_raw_control(force_cpu: bool):
    # reader THREAD + events, not blocking readline: a hung PJRT init
    # prints nothing, and a blocked readline would defeat both timeouts
    # (the round-1 failure mode this supervisor exists for)
    import threading

    env = dict(os.environ, _BENCH_RAW="1")
    env.pop("LIBTPU_INIT_ARGS", None)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ray_tpu/xla_cache")
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["_BENCH_FORCE_CPU"] = "1"
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE, text=True, env=env,
                            start_new_session=True)
    lines: list = []
    got_ready = threading.Event()
    done = threading.Event()

    def reader():
        for line in proc.stdout:
            line = line.strip()
            if line.startswith(READY_MARKER):
                got_ready.set()
            elif line:
                lines.append(line)
        done.set()

    threading.Thread(target=reader, daemon=True).start()
    if not got_ready.wait(INIT_TIMEOUT_S):
        _kill_group(proc)
        return None, "raw control: backend init timed out"
    if not done.wait(RUN_TIMEOUT_S):
        _kill_group(proc)
        return None, "raw control: run timed out"
    proc.wait()
    for line in reversed(lines):
        try:
            result = json.loads(line)
        except ValueError:
            continue
        if result.get("error"):
            return None, f"raw control error: {result['error']}"
        return result, None
    return None, f"raw control exited rc={proc.returncode} w/o JSON"


# ------------------------------------------------- framework path (headline)

def _train_loop_per_worker(config):
    """Runs inside the framework-managed TPU worker."""
    from ray_tpu.air import session

    on_tpu = config["on_tpu"]
    if not on_tpu:
        # CPU fallback: pin the platform in the WORKER too — env
        # inheritance alone does not stop the tunneled TPU backend
        _force_cpu_platform()
    shard = session.get_dataset_shard("train")

    make_feed = None
    if shard is not None:
        def make_feed(trainer, batch_size):
            # Synthetic-data regime, same as the reference benchmark
            # (resnet50_ray_air synthetic mode): the Dataset's batches are
            # transferred once via the double-buffered device iterator and
            # then cycled device-resident. (On this box host->device rides
            # a network tunnel at ~40MB/s, so a per-step feed would measure
            # the tunnel, not the framework; on a real host the same
            # iter_device_batches call overlaps per-step DMA instead.)
            import itertools
            cached = list(shard.iter_device_batches(
                batch_size=batch_size,
                sharding=trainer.batch_shardings,
                drop_last=True, pad_to_batch=False))
            return itertools.cycle(cached)
    res = bench_loop(on_tpu, make_feed=make_feed)
    try:
        res.update(gpt2_loop(on_tpu))
    except Exception as e:  # the GPT-2 row must not sink the headline
        res["gpt2_error"] = f"{type(e).__name__}: {e}"[:200]
    session.report(res)


def _framework_main():
    """Driver: CPU-pinned; the TPU belongs to the train worker."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    if os.environ.get("_BENCH_FORCE_CPU"):
        # workers inherit the env — drop the TPU args for them too
        os.environ.pop("LIBTPU_INIT_ARGS", None)

    import ray_tpu
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

    force_cpu = bool(os.environ.get("_BENCH_FORCE_CPU"))
    n_tpus = 0 if force_cpu else 1
    import numpy as np

    from ray_tpu import data as rt_data

    ray_tpu.init(num_cpus=4, num_tpus=n_tpus,
                 object_store_memory=2 * 1024**3,
                 _system_config={"prestart_workers": False})
    try:
        # synthetic ImageNet shard: uint8 images (the wire format a real
        # ingest pipeline would ship), labels int32
        if n_tpus:
            n_imgs, img = 1024, 224
        else:
            n_imgs, img = 64, 32
        rng = np.random.default_rng(0)
        items = [{"image": rng.integers(0, 256, (img, img, 3),
                                        dtype=np.uint8),
                  "label": np.int32(rng.integers(0, 1000))}
                 for _ in range(n_imgs)]
        train_ds = rt_data.from_items(items, parallelism=8)

        resources = {"TPU": 1} if n_tpus else {"CPU": 1}
        trainer = DataParallelTrainer(
            _train_loop_per_worker,
            train_loop_config={"on_tpu": bool(n_tpus)},
            datasets={"train": train_ds},
            scaling_config=ScalingConfig(num_workers=1,
                                         resources_per_worker=resources))
        result = trainer.fit()
        if result.error:
            raise RuntimeError(result.error)
        return result.metrics
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------- data-ingest microbench

def _data_ingest_main():
    """Data-ingest microbenchmark (ISSUE 1): N blocks through a
    read(sleep) -> map(sleep) two-stage chain, bulk vs streaming
    executor.  Reports blocks/s and time-to-first-batch per mode.  The
    two map_batches stages use different remote_opts so they do NOT fuse
    — the stage skew is what bulk execution serializes and streaming
    overlaps.  Prints one JSON line; also merged into the flagship line
    as ingest_* keys by the supervisor."""
    _force_cpu_platform()
    import numpy as np

    import ray_tpu
    from ray_tpu import data as rt_data

    n_blocks = int(os.environ.get("BENCH_INGEST_BLOCKS", 16))
    read_s = float(os.environ.get("BENCH_INGEST_READ_S", 0.2))
    map_s = float(os.environ.get("BENCH_INGEST_MAP_S", 0.2))
    n_cpus = 4

    def read_sim(b):
        time.sleep(read_s)
        return b

    def map_sim(b):
        time.sleep(map_s)
        return b

    ray_tpu.init(num_cpus=n_cpus, object_store_memory=512 * 1024**2,
                 _system_config={"prestart_workers": False})
    out = {}
    try:
        # warm the worker pool so neither mode pays spawn cost
        rt_data.range(8, parallelism=8).map(lambda x: x).take_all()
        # streaming keeps in-flight ~= cores so the head map task is not
        # queued behind the whole read wave
        os.environ["RTPU_DATA_MAX_INFLIGHT_TASKS"] = str(n_cpus)
        for mode, key in (("0", "bulk"), ("1", "streaming")):
            os.environ["RTPU_DATA_STREAMING"] = mode
            t0 = time.perf_counter()
            ds = (rt_data.range(n_blocks * 16, parallelism=n_blocks)
                  .map_batches(read_sim, batch_format="numpy", num_cpus=1)
                  .map_batches(map_sim, batch_format="numpy"))
            it = ds.iter_batches(batch_size=16, batch_format="numpy")
            first = next(it)
            t_first = time.perf_counter() - t0
            n = 1 + sum(1 for _ in it)
            t_total = time.perf_counter() - t0
            assert n == n_blocks and len(first) == 16
            out[f"{key}_time_to_first_batch_s"] = round(t_first, 3)
            out[f"{key}_total_s"] = round(t_total, 3)
            out[f"{key}_blocks_per_s"] = round(n_blocks / t_total, 2)
        out["blocks"] = n_blocks
        out["chain_latency_s"] = read_s + map_s
        out["ttfb_speedup"] = round(
            out["bulk_time_to_first_batch_s"]
            / out["streaming_time_to_first_batch_s"], 2)
        out["throughput_vs_bulk"] = round(
            out["streaming_blocks_per_s"] / out["bulk_blocks_per_s"], 3)
    finally:
        ray_tpu.shutdown()
    print(json.dumps({"metric": "data_ingest", **out}), flush=True)


def _run_ingest_bench():
    """Run the ingest microbench in a subprocess (CPU-only, cheap) and
    return its keys prefixed ingest_*, or {} on any failure — it must
    never sink the flagship line."""
    env = dict(os.environ, _BENCH_DATA_INGEST="1", JAX_PLATFORMS="cpu")
    env.pop("LIBTPU_INIT_ARGS", None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, text=True, timeout=180, env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                row = json.loads(line)
                if row.get("metric") == "data_ingest":
                    row.pop("metric")
                    return {f"ingest_{k}": v for k, v in row.items()}
    except Exception:
        pass
    return {}


# --------------------------------------------------- checkpoint microbench

def _ckpt_bench_main():
    """Checkpoint-engine microbench (_BENCH_CKPT=1): how long the train
    step is blocked per save, sync vs async, on a multi-MB pytree.

    Each mode runs the same loop: mutate state, save, then "train" for
    BENCH_CKPT_STEP_MS (the compute an async writer overlaps). Sync mode
    (RTPU_CKPT_ASYNC=0) blocks for snapshot+write+checksum+fsync+commit;
    async blocks only for the host snapshot (+ any backpressure when the
    previous write hasn't landed). No cluster needed; one JSON line."""
    import shutil
    import tempfile

    import numpy as np

    from ray_tpu.checkpoint import AsyncCheckpointer, CheckpointManager

    mb = float(os.environ.get("BENCH_CKPT_MB", 64))
    saves = int(os.environ.get("BENCH_CKPT_SAVES", 4))
    step_ms = float(os.environ.get("BENCH_CKPT_STEP_MS", 200))
    n_leaves = 8
    leaf_elems = max(1, int(mb * 1024 ** 2 / 4 / n_leaves))
    rng = np.random.default_rng(0)
    state = {"params": {f"w{i}": rng.standard_normal(leaf_elems)
                        .astype(np.float32) for i in range(n_leaves)},
             "step": np.zeros((), np.int32)}
    total_mb = sum(a.nbytes for a in state["params"].values()) / 1024 ** 2
    out = {"pytree_mb": round(total_mb, 1), "saves": saves,
           "step_ms": step_ms}
    for mode in ("sync", "async"):
        os.environ["RTPU_CKPT_ASYNC"] = "1" if mode == "async" else "0"
        root = tempfile.mkdtemp(prefix=f"rtpu_ckpt_bench_{mode}_")
        try:
            mgr = CheckpointManager(root, num_to_keep=2)
            ck = AsyncCheckpointer(mgr)
            blocked = []
            t_all = time.perf_counter()
            for s in range(saves):
                state["step"] = state["step"] + 1
                t0 = time.perf_counter()
                ck.save(s, state)
                blocked.append(time.perf_counter() - t0)
                time.sleep(step_ms / 1e3)  # the overlapped train step
            ck.finalize()
            wall = time.perf_counter() - t_all
            assert mgr.latest_committed() == saves - 1, \
                f"{mode}: expected step {saves - 1} committed"
            stats = ck.stats
            out[f"{mode}_blocked_ms_per_save"] = round(
                1e3 * sum(blocked) / saves, 2)
            out[f"{mode}_snapshot_ms_mean"] = round(
                sum(st.snapshot_ms for st in stats) / saves, 2)
            out[f"{mode}_write_ms_mean"] = round(
                sum(st.write_ms for st in stats) / saves, 2)
            out[f"{mode}_wall_s"] = round(wall, 3)
        finally:
            shutil.rmtree(root, ignore_errors=True)
    out["blocked_frac_vs_sync"] = round(
        out["async_blocked_ms_per_save"]
        / max(out["sync_blocked_ms_per_save"], 1e-9), 4)
    print(json.dumps({"metric": "checkpoint", **out}), flush=True)


def _chaos_bench_main():
    """Chaos smoke (_BENCH_CHAOS=1): fault→detect→recover latency for
    the two headline faults.

    Phase A — worker SIGKILL at a chosen task count (chaos schedule):
    detect = the raylet's WORKER_DIED event vs the kill timestamp in the
    chaos log; recover = the killed task's retried result landing.

    Phase B — preemption notice on a worker node: drain time (the
    raylet's own NODE_PREEMPTED accounting) and failover time (notice →
    GCS marks the node dead) from the structured event stream.

    One JSON line; recorded in PERF.md."""
    import tempfile

    import ray_tpu
    from ray_tpu._private import chaos
    from ray_tpu._private import worker as wmod
    from ray_tpu._private.cluster_utils import Cluster

    out = {}

    def events(w, label):
        evs = w.call_sync(w.gcs, "list_events", {"limit": 1000})
        return [e for e in evs if e.get("label") == label]

    # ---- phase A: worker kill detect/recover
    log_path = os.path.join(tempfile.mkdtemp(prefix="rtpu_chaos_bench_"),
                            "chaos.jsonl")
    os.environ["RTPU_CHAOS"] = json.dumps({"seed": 1, "schedule": [
        {"site": "worker.execute", "op": "kill", "at": 3,
         "proc": "worker"}]})
    os.environ["RTPU_CHAOS_LOG"] = log_path
    ray_tpu.init(num_cpus=1, object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_retries=3)
        def unit(x):
            return x

        t0 = time.perf_counter()
        for i in range(6):
            assert ray_tpu.get(unit.remote(i), timeout=120) == i
        out["workload_wall_s"] = round(time.perf_counter() - t0, 3)
        w = wmod._global_worker
        kill = next(r for r in chaos.read_log(log_path)
                    if r["op"] == "kill")
        died = events(w, "WORKER_DIED")
        assert died, "worker death was never detected"
        out["worker_kill_detect_ms"] = round(
            1e3 * (died[0]["timestamp"] - kill["ts"]), 1)
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RTPU_CHAOS", None)
        os.environ.pop("RTPU_CHAOS_LOG", None)

    # ---- phase B: preemption drain + failover
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        info = cluster.add_node(num_cpus=2, resources={"spot": 1})
        cluster.connect()
        cluster.wait_for_nodes()
        w = wmod._global_worker

        @ray_tpu.remote(max_retries=3, resources={"spot": 0.1})
        def on_spot(x):
            return x + 1

        assert ray_tpu.get(on_spot.remote(1), timeout=60) == 2
        t0 = time.time()
        cluster.preempt_node(info, grace_s=2.0)
        deadline = time.monotonic() + 30
        dead_at = None
        while time.monotonic() < deadline:
            n = next(n for n in ray_tpu.nodes()
                     if n["node_id"] == info["node_id"])
            if not n["alive"]:
                dead_at = time.time()
                break
            time.sleep(0.1)
        assert dead_at is not None, "preempted node never died"
        notice = events(w, "PREEMPTION_NOTICE")
        preempted = events(w, "NODE_PREEMPTED")
        assert notice and preempted
        out["preempt_drain_s"] = round(
            preempted[0]["fields"].get("drain_s", 0.0), 3)
        out["preempt_failover_s"] = round(dead_at - t0, 3)
        out["preempt_notice_to_dead_s"] = round(
            dead_at - notice[0]["timestamp"], 3)
    finally:
        cluster.shutdown()
    print(json.dumps({"metric": "chaos", **out}), flush=True)


# ------------------------------------------------------ state-engine bench


def _dag_bench_main():
    """Compiled-DAG bench (_BENCH_DAG=1): 3-stage actor pipeline,
    compiled channels vs dynamic ``.execute()`` dispatch (ROADMAP item
    3; gates >=5x per-hop latency and >=3x pipelined throughput on the
    1-core CI box). Also reports a 256 KB-payload variant (plasmax
    ring-slot path) and the ring-reuse segment delta. One JSON line;
    recorded in PERF.md."""
    import statistics

    import numpy as np

    import ray_tpu
    from ray_tpu._private import worker as wmod
    from ray_tpu.dag import InputNode

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    out = {}
    try:
        @ray_tpu.remote
        class Stage:
            def step(self, x):
                return x

        with InputNode() as inp:
            s1, s2, s3 = Stage.bind(), Stage.bind(), Stage.bind()
            pipe = s3.step.bind(s2.step.bind(s1.step.bind(inp)))

        def lat(fn, n):
            xs = []
            for _ in range(n):
                t0 = time.perf_counter()
                fn()
                xs.append(time.perf_counter() - t0)
            return statistics.median(xs)

        # dynamic: per-exec latency + pipelined throughput (refs
        # submitted without waiting, gathered in one get)
        ray_tpu.get(pipe.execute(0))  # actor warmup
        dyn_exec_s = lat(lambda: ray_tpu.get(pipe.execute(0)), 50)
        n = 200
        t0 = time.perf_counter()
        refs = [pipe.execute(i) for i in range(n)]
        ray_tpu.get(refs, timeout=300)
        dyn_rate = n / (time.perf_counter() - t0)

        cpipe = pipe.compile()
        assert cpipe._compiled, "pipeline failed to compile"
        cpipe.execute(0)  # channel warmup
        cmp_exec_s = lat(lambda: cpipe.execute(0), 200)
        t0 = time.perf_counter()
        futs = [cpipe.execute_async(i) for i in range(1000)]
        for f in futs:
            f.result(60)
        cmp_rate = 1000 / (time.perf_counter() - t0)

        out["dynamic_per_hop_us"] = round(1e6 * dyn_exec_s / 3, 1)
        out["compiled_per_hop_us"] = round(1e6 * cmp_exec_s / 3, 1)
        out["per_hop_speedup"] = round(dyn_exec_s / cmp_exec_s, 2)
        out["dynamic_pipelined_per_s"] = round(dyn_rate, 1)
        out["compiled_pipelined_per_s"] = round(cmp_rate, 1)
        out["throughput_speedup"] = round(cmp_rate / dyn_rate, 2)

        # 256 KB activations through the plasmax ring slots: steady-state
        # latency + the segment-usage delta across 100 triggers (must be
        # flat — seal/unseal reuse, docs/COMPILED_DAGS.md)
        arr = np.zeros(32 * 1024, dtype=np.float64)
        for _ in range(4):  # >= ring depth: lazy slots exist before t0
            cpipe.execute(arr)
        w = wmod._global_worker
        s0 = w.plasma.stats()
        big_s = lat(lambda: cpipe.execute(arr), 100)
        s1_ = w.plasma.stats()
        out["compiled_256k_per_hop_us"] = round(1e6 * big_s / 3, 1)
        out["ring_used_bytes_delta"] = \
            s1_["used_bytes"] - s0["used_bytes"]
        out["ring_created_delta"] = \
            s1_["num_created"] - s0["num_created"]
        cpipe.teardown()
    finally:
        ray_tpu.shutdown()
    out["gate_per_hop_5x"] = out["per_hop_speedup"] >= 5.0
    out["gate_throughput_3x"] = out["throughput_speedup"] >= 3.0
    print(json.dumps({"metric": "compiled_dag", **out}), flush=True)


def _net_bench_main():
    """Cross-node transport bench (_BENCH_NET=1): two raylets on one
    machine restricted to TCP (distinct ``RTPU_NODE_IP`` aliases +
    ``RTPU_NET_FORCE_TCP``, the same harness as tests/test_netx.py).
    Measures (a) bulk object pull throughput through the netx ``px_*``
    plane vs the asyncio chunk-RPC pull baseline — gated against the
    63 MiB/s SCALE.md round-5 aggregate — (b) direct-lane actor-call
    RTT across "hosts", (c) compiled-DAG cross-host execute latency.
    Env: NET_BENCH_SMOKE=1 shrinks the run (CI smoke); NET_BENCH_MB
    overrides the object size. One JSON line; recorded in PERF.md."""
    import statistics

    import numpy as np

    import ray_tpu
    from ray_tpu._private import netx
    from ray_tpu._private.cluster_utils import Cluster
    from ray_tpu._private.netx import endpoints
    from ray_tpu.dag import InputNode

    smoke = bool(os.environ.get("NET_BENCH_SMOKE"))
    mb = int(os.environ.get("NET_BENCH_MB", "32" if smoke else "256"))
    iters = 30 if smoke else 200
    store = max(512, 3 * mb) * 1024 * 1024

    def two_host_cluster(netx_on):
        os.environ["RTPU_NODE_IP"] = "127.0.0.1"
        os.environ["RTPU_NET_FORCE_TCP"] = "1"
        os.environ["RTPU_NETX"] = "1" if netx_on else "0"
        endpoints._reset_for_tests()
        netx.reset_client_for_tests()
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2,
                                          "resources": {"hosta": 4},
                                          "object_store_memory": store})
        cluster.add_node(num_cpus=2, resources={"hostb": 4},
                         object_store_memory=store,
                         env_overrides={
                             "RTPU_NODE_IP": "127.0.0.2",
                             "RTPU_NET_FORCE_TCP": "1",
                             "RTPU_NETX": "1" if netx_on else "0"})
        cluster.connect()
        cluster.wait_for_nodes()
        return cluster

    def pull_mib_s():
        # object sealed on "host" B first (the probe task runs next to
        # it, zero-copy), THEN the driver-side get times the pure
        # cross-host transfer + local map
        @ray_tpu.remote(resources={"hostb": 1})
        def make(n):
            return np.ones(n, dtype=np.uint8)

        @ray_tpu.remote(resources={"hostb": 1})
        def probe(x):
            return int(x[0])

        n = mb * 1024 * 1024
        ref = make.remote(n)
        assert ray_tpu.get(probe.remote(ref), timeout=600) == 1
        t0 = time.perf_counter()
        arr = ray_tpu.get(ref, timeout=600)
        dt = time.perf_counter() - t0
        assert arr.shape == (n,)
        return mb / dt

    out = {"object_mb": mb}
    cluster = two_host_cluster(netx_on=False)
    try:
        out["asyncio_pull_mib_s"] = round(pull_mib_s(), 1)
    finally:
        cluster.shutdown()

    cluster = two_host_cluster(netx_on=True)
    try:
        out["netx_pull_mib_s"] = round(pull_mib_s(), 1)

        @ray_tpu.remote(resources={"hostb": 1})
        class Echo:
            def e(self, x):
                return x

        a = Echo.remote()
        ray_tpu.get(a.e.remote(0), timeout=120)  # lane warm
        xs = []
        for i in range(iters):
            t0 = time.perf_counter()
            ray_tpu.get(a.e.remote(i), timeout=60)
            xs.append(time.perf_counter() - t0)
        out["actor_call_rtt_us"] = round(1e6 * statistics.median(xs), 1)

        with InputNode() as inp:
            s1 = Echo.options(resources={"hosta": 1}).bind()
            s2 = Echo.options(resources={"hostb": 1}).bind()
            pipe = s2.e.bind(s1.e.bind(inp))
        cpipe = pipe.compile()
        try:
            assert cpipe._compiled, "cross-host pipeline failed to compile"
            cpipe.execute(0)  # channel warmup
            xs = []
            for i in range(iters):
                t0 = time.perf_counter()
                cpipe.execute(i)
                xs.append(time.perf_counter() - t0)
            out["dag_cross_host_exec_us"] = round(
                1e6 * statistics.median(xs), 1)
        finally:
            cpipe.teardown()
    finally:
        cluster.shutdown()
        for k in ("RTPU_NODE_IP", "RTPU_NET_FORCE_TCP", "RTPU_NETX"):
            os.environ.pop(k, None)

    out["pull_speedup_vs_asyncio"] = round(
        out["netx_pull_mib_s"] / max(out["asyncio_pull_mib_s"], 0.1), 2)
    # SCALE.md round-5 broadcast baseline: 63 MiB/s aggregate on the
    # asyncio chunk-RPC path — the netx plane must beat it outright
    out["gate_pull_63mibs"] = out["netx_pull_mib_s"] >= 63.0
    print(json.dumps({"metric": "net", **out}), flush=True)


def _state_bench_main():
    """State-engine microbench (_BENCH_STATE=1): with 10k+ drained
    tasks in the GCS task table, measure (a) list_tasks first-page p50
    latency, (b) a full paginated walk, (c) the naive full-dump (one
    legacy RPC carrying the whole table — what every list call did
    before pagination), and (d) the head-node (GCS) RSS delta from
    holding the bounded table. One JSON line; recorded in PERF.md."""
    import statistics
    import subprocess as sp

    import ray_tpu
    from ray_tpu._private import worker as wmod
    from ray_tpu.experimental.state import api as state_api

    n = int(os.environ.get("STATE_BENCH_TASKS", 10_000))

    def gcs_rss() -> int:
        pid = int(sp.check_output(
            ["pgrep", "-f", "ray_tpu._private.gcs_main"]).split()[0])
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) * 1024
        return 0

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    out = {}
    try:
        rss0 = gcs_rss()

        @ray_tpu.remote
        def sb_noop(i):
            return i

        t0 = time.perf_counter()
        ray_tpu.get(sb_noop.remote_batch([(i,) for i in range(n)]),
                    timeout=900)
        out["drain_s"] = round(time.perf_counter() - t0, 2)
        # wait for the event pipeline to settle into the table
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            s = state_api.summarize_tasks()
            tracked = s["by_state"].get("FINISHED", 0) + s["dropped"]
            if tracked >= n:
                break
            time.sleep(0.5)
        out["tasks_tracked"] = s["total"]
        out["tasks_dropped"] = s["dropped"]
        out["gcs_rss_delta_mb"] = round((gcs_rss() - rss0) / 1e6, 1)

        lat = []
        for _ in range(30):
            t0 = time.perf_counter()
            page = state_api.list_tasks(page_size=1000)
            lat.append(time.perf_counter() - t0)
        assert len(page) == 1000
        out["page1k_p50_ms"] = round(
            1e3 * statistics.median(lat), 2)
        t0 = time.perf_counter()
        full = state_api.list_tasks()
        out["paginated_walk_s"] = round(time.perf_counter() - t0, 3)
        out["rows_walked"] = len(full)
        # naive legacy path: the whole table in ONE rpc reply
        w = wmod._global_worker
        lat = []
        for _ in range(10):
            t0 = time.perf_counter()
            rows = w.call_sync(w.gcs, "list_tasks", {}, timeout=120)
            lat.append(time.perf_counter() - t0)
        assert len(rows) == len(full)
        out["naive_full_dump_p50_ms"] = round(
            1e3 * statistics.median(lat), 2)
    finally:
        ray_tpu.shutdown()
    # deterministic table-cost measurement (live GCS RSS deltas get
    # absorbed by allocator arenas): n records through a fresh table
    # in this process
    from ray_tpu._private.gcs import TaskEventTable

    def rss_self() -> int:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) * 1024
        return 0

    r0 = rss_self()
    table = TaskEventTable(cap=max(n, 32768))
    now = time.time()
    for i in range(n):
        tid = f"{i:032x}"
        table.apply({"task_id": tid, "state": "PENDING_SCHEDULING",
                     "ts": now, "name": "sb_noop", "job_id": "01"})
        table.apply({"task_id": tid, "state": "RUNNING", "ts": now,
                     "node_id": "n" * 32, "worker_pid": 1234})
        table.apply({"task_id": tid, "state": "FINISHED", "ts": now})
    out["table_cost_mb"] = round((rss_self() - r0) / 1e6, 2)
    print(json.dumps({"metric": "state_engine", "n_tasks": n, **out}),
          flush=True)


# ------------------------------------------------------- serve data-plane bench

class _BenchSeqCounter:
    """Named-actor sequence so the Nth-constructed replica can tell it is
    the Nth (the skewed-replica picker below)."""

    def __init__(self):
        self.n = 0

    def next(self):
        self.n += 1
        return self.n


def _serve_bench_main():
    """Serve data-plane benchmark (_BENCH_SERVE=1): closed-loop clients
    through the handle and HTTP paths, reporting RPS/p50/p99 for
    round-robin vs power-of-two-choices routing under skewed replica
    load, and fixed-window vs adaptive micro-batching (idle p50 +
    loaded RPS). CPU-only; one JSON line."""
    _force_cpu_platform()
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve

    duration = float(os.environ.get("BENCH_SERVE_DURATION", 3.0))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
    service_ms = float(os.environ.get("BENCH_SERVE_SERVICE_MS", 5.0))
    skew = float(os.environ.get("BENCH_SERVE_SKEW", 10.0))

    def closed_loop(fn, n_clients, dur):
        lat, errors = [], [0]
        lock = threading.Lock()
        stop = time.perf_counter() + dur

        def worker():
            local = []
            while time.perf_counter() < stop:
                t0 = time.perf_counter()
                try:
                    fn()
                except Exception:
                    with lock:
                        errors[0] += 1
                    continue
                local.append(time.perf_counter() - t0)
            with lock:
                lat.extend(local)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not lat:
            return {"rps": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                    "errors": errors[0]}
        arr = np.asarray(lat)
        return {"rps": round(len(lat) / dur, 1),
                "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 2),
                "errors": errors[0]}

    class SkewedEcho:
        """One replica serves at service_s, its sibling skew× slower —
        the asymmetry blind round-robin cannot see."""

        def __init__(self, service_s, skew_factor):
            import ray_tpu as rt
            try:
                ctr = rt.get_actor("BENCH_SERVE_SEQ")
            except Exception:
                try:
                    ctr = rt.remote(name="BENCH_SERVE_SEQ",
                                    lifetime="detached")(
                        _BenchSeqCounter).remote()
                except Exception:  # sibling replica won the race
                    ctr = rt.get_actor("BENCH_SERVE_SEQ")
            idx = rt.get(ctr.next.remote())
            self.delay = service_s * (skew_factor if idx % 2 == 0
                                      else 1.0)

        def __call__(self, x):
            time.sleep(self.delay)
            return x

    def make_batched(adaptive_mode):
        class BatchedEcho:
            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05,
                         adaptive=adaptive_mode, submit_timeout_s=30.0)
            def run(self, items):
                time.sleep(0.002)  # one fixed-cost "model step" per flush
                return list(items)

            def __call__(self, x):
                return self.run(x)
        return BatchedEcho

    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024,
                 _system_config={"prestart_workers": False})
    out = {"duration_s": duration, "clients": clients,
           "service_ms": service_ms, "skew": skew}
    try:
        # ---- routing: 2 skewed replicas, handle path, rr vs p2c ----
        h = serve.run(
            serve.deployment(num_replicas=2, max_concurrent_queries=32)(
                SkewedEcho).bind(service_ms / 1e3, skew),
            name="routing", route_prefix="/skew", http_port=8200)

        def handle_call():
            ray_tpu.get(h.remote(1), timeout=30.0)

        for _ in range(8):
            handle_call()  # warm replicas + router telemetry
        for policy in ("round_robin", "p2c"):
            os.environ["RTPU_SERVE_ROUTING"] = policy
            time.sleep(1.2)  # let a fresh replica_load long-poll land
            st = closed_loop(handle_call, clients, duration)
            for k, v in st.items():
                out[f"route_{policy}_{k}"] = v
        if out["route_round_robin_rps"]:
            out["p2c_vs_rr_rps"] = round(
                out["route_p2c_rps"] / out["route_round_robin_rps"], 3)

        # ---- HTTP path (p2c), same skewed deployment ----
        import urllib.request
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        port = ray_tpu.get(proxy.get_port.remote())

        def http_call():
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/skew?x=1", timeout=30).read()

        http_call()
        st = closed_loop(http_call, clients, duration)
        for k, v in st.items():
            out[f"http_{k}"] = v

        # ---- batching: fixed window vs adaptive ----
        for mode in ("fixed", "adaptive"):
            dep = serve.deployment(
                num_replicas=1, max_concurrent_queries=64)(
                make_batched(mode == "adaptive"))
            hb = serve.run(dep.options(name=f"Batched_{mode}").bind(),
                           name=f"batch_{mode}",
                           route_prefix=f"/batch_{mode}", http_port=None)

            def batch_call(hb=hb):
                ray_tpu.get(hb.remote(1), timeout=30.0)

            batch_call()
            idle = closed_loop(batch_call, 1, duration)  # idle queue
            loaded = closed_loop(batch_call, 2 * clients, duration)
            out[f"batch_{mode}_idle_p50_ms"] = idle["p50_ms"]
            out[f"batch_{mode}_idle_p99_ms"] = idle["p99_ms"]
            out[f"batch_{mode}_rps"] = loaded["rps"]
            out[f"batch_{mode}_p99_ms"] = loaded["p99_ms"]
        if out["batch_adaptive_idle_p50_ms"]:
            out["adaptive_idle_p50_speedup"] = round(
                out["batch_fixed_idle_p50_ms"]
                / out["batch_adaptive_idle_p50_ms"], 2)
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()
    print(json.dumps({"metric": "serve_dataplane", **out}), flush=True)


# ------------------------------------------------------- serve HA bench

def _serve_ha_bench_main():
    """Serve control-plane HA benchmark (_BENCH_SERVE_HA=1): request
    success rate and latency under sustained load during (a) a
    health-gated rolling update and (b) a controller SIGKILL +
    journal recovery. The acceptance bar is ZERO failed requests in
    both windows — the data plane must not notice the control plane.
    CPU-only; one JSON line."""
    _force_cpu_platform()
    import signal
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve

    duration = float(os.environ.get("BENCH_SERVE_HA_DURATION", 8.0))
    clients = int(os.environ.get("BENCH_SERVE_HA_CLIENTS", 6))

    def versioned(v):
        @serve.deployment(num_replicas=2, name="HA",
                          max_concurrent_queries=32,
                          user_config={"v": v},
                          graceful_shutdown_timeout_s=10.0)
        class HA:
            def __init__(self):
                self.v = None

            def reconfigure(self, cfg):
                self.v = cfg["v"]

            def __call__(self, x):
                time.sleep(0.005)
                return self.v

        return HA

    class _Phase:
        """Closed-loop load whose samples are binned into named phases
        by wall-clock markers."""

        def __init__(self):
            self.lock = threading.Lock()
            self.samples = []  # (t_done, latency_s, ok)
            self.stop = threading.Event()

        def worker(self, fn):
            while not self.stop.is_set():
                t0 = time.perf_counter()
                ok = True
                try:
                    fn()
                except Exception:
                    ok = False
                with self.lock:
                    self.samples.append(
                        (time.time(), time.perf_counter() - t0, ok))

        def window(self, t_start, t_end):
            with self.lock:
                rows = [(lat, ok) for t, lat, ok in self.samples
                        if t_start <= t <= t_end]
            lats = [lat for lat, ok in rows if ok]
            return {
                "total": len(rows),
                "failed": sum(1 for _, ok in rows if not ok),
                "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 2)
                if lats else 0.0,
                "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 2)
                if lats else 0.0,
            }

    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024,
                 _system_config={"prestart_workers": False})
    out = {"duration_s": duration, "clients": clients}
    try:
        h = serve.run(versioned(1).bind(), http_port=None)
        ray_tpu.get(h.remote(0), timeout=30.0)
        ph = _Phase()

        def call():
            ray_tpu.get(h.remote(0), timeout=30.0)

        threads = [threading.Thread(target=ph.worker, args=(call,))
                   for _ in range(clients)]
        for t in threads:
            t.start()
        time.sleep(duration / 4)

        # (a) health-gated rolling update under load
        t0 = time.time()
        serve.run(versioned(2).bind(), http_port=None,
                  _blocking_timeout=120.0)
        t1 = time.time()
        out["rolling_s"] = round(t1 - t0, 2)
        for k, v in ph.window(t0, t1).items():
            out[f"rolling_{k}"] = v
        time.sleep(duration / 4)

        # (b) controller SIGKILL + journal recovery under load
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        pid = ray_tpu.get(ctrl.get_controller_info.remote(),
                          timeout=10.0)["pid"]
        t2 = time.time()
        os.kill(pid, signal.SIGKILL)
        recovered = None
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                info = ray_tpu.get(ctrl.get_controller_info.remote(),
                                   timeout=5.0)
                st = ray_tpu.get(
                    ctrl.get_deployment_statuses.remote(), timeout=5.0)
                if info["pid"] != pid and info["recovered"] and \
                        st.get("HA", {}).get("status") == "HEALTHY":
                    recovered = time.time()
                    break
            except Exception:
                pass
            time.sleep(0.2)
        t3 = time.time()
        out["ctrl_recovery_s"] = round(
            (recovered or t3) - t2, 2)
        out["ctrl_recovered"] = bool(recovered)
        for k, v in ph.window(t2, t3).items():
            out[f"ctrl_kill_{k}"] = v
        time.sleep(duration / 4)
        ph.stop.set()
        for t in threads:
            t.join()
        whole = ph.window(0, time.time())
        out["overall_total"] = whole["total"]
        out["overall_failed"] = whole["failed"]
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()
    print(json.dumps({"metric": "serve_ha", **out}), flush=True)


def _gameday_bench_main():
    """Game-day SLO bench (_BENCH_GAMEDAY=1): run the builtin scenarios
    end to end — open-loop load with diurnal/flash-crowd shapes + the
    seeded chaos schedule + rolling updates — and report CLIENT-side
    p99/p99.9, error-budget burn, failed-request count, and whether the
    ledger reconciled exactly with the server-side records
    (docs/GAMEDAY.md). CPU-only; one JSON line.

    Env: BENCH_GAMEDAY_SCENARIOS (default "flagship,flash-crowd"),
    BENCH_GAMEDAY_SCALE (phase-duration multiplier, default 1.0)."""
    _force_cpu_platform()
    from ray_tpu.gameday import load_scenario, run_scenario

    names = [n.strip() for n in os.environ.get(
        "BENCH_GAMEDAY_SCENARIOS", "flagship,flash-crowd").split(",")
        if n.strip()]
    scale = float(os.environ.get("BENCH_GAMEDAY_SCALE", 1.0))
    out = {"scale": scale, "scenarios": {}}
    for name in names:
        sc = load_scenario(name)
        result = run_scenario(sc, scale=scale, dashboard_port=18471)
        rep = result.report
        o = rep["overall"]
        recon = rep["reconciliation"]
        out["scenarios"][name] = {
            "seed": rep["seed"],
            "requests": o["total"],
            "admitted": o["admitted"],
            "shed": o["shed"],
            "failed": o["failed"],
            "p50_ms": o["p50_ms"],
            "p99_ms": o["p99_ms"],
            "p999_ms": o["p999_ms"],
            "availability_burn": rep["slo"]["availability_burn"],
            "latency_burn": rep["slo"].get("latency_burn"),
            "reconciled": recon["ok"],
            "chaos_fired": len(rep.get("chaos_fired") or []),
            "passed": rep["passed"],
        }
    print(json.dumps({"metric": "gameday", **out}), flush=True)


def _llm_bench_main():
    """LLM serving bench (_BENCH_LLM=1): the continuous-batching
    engine vs the static flush-by-window baseline under a skewed
    open-loop load (Poisson arrivals, bounded-Pareto output lengths),
    plus the paged-attention kernel numerics check. One JSON line:
    tokens/s, p50/p99 time-to-first-token (measured from the SCHEDULED
    arrival — open-loop discipline), makespan, and the gates the
    acceptance criteria name: continuous >= 1.5x static tokens/s with
    better p99 TTFT; paged kernel == whole-kv reference numerics.

    Env: LLM_BENCH_SMOKE=1 shrinks the run (CI smoke);
    LLM_BENCH_DURATION_S / LLM_BENCH_RPS override the load window.

    The toy adapter emulates model cost (3 ms/step + 0.2 ms/sequence;
    0.05 ms/prefill token): per-step cost is mostly FIXED, which is
    exactly the regime where continuous batching wins — a static batch
    runs its stragglers nearly alone while admitted work waits.

    Two fleet-serving sections ride along (docs/LLM_SERVING.md):
    radix prefix cache (warm vs cold under a Zipf-skewed
    shared-system-prompt tenant mix; gates >= 1.3x tokens/s, no-worse
    TTFT p99, identical outputs, hit ratio reported) and
    prefill/decode disaggregation (1 prefill + 1 decode engine with
    KV handoff vs 2 unified engines round-robin; gate: disagg TPOT
    p99 <= unified — decode never pays a prefill bubble)."""
    _force_cpu_platform()
    import random
    import threading

    from ray_tpu.serve.llm import (EngineConfig, LLMEngine,
                                   SamplingParams, ToyAdapter)

    smoke = bool(os.environ.get("LLM_BENCH_SMOKE"))
    # offered tokens/s must exceed the STATIC baseline's capacity
    # (~210 tok/s at these step costs: a flush-by-window batch runs at
    # its longest member's length) while staying well under the
    # continuous engine's (~1.7k tok/s) — that's the regime the gate
    # measures: same hardware budget, saturation only for the baseline
    duration = float(os.environ.get("LLM_BENCH_DURATION_S",
                                    2.5 if smoke else 10.0))
    rate = float(os.environ.get("LLM_BENCH_RPS",
                                25.0 if smoke else 40.0))
    rng = random.Random(1234)
    arrivals = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration:
            break
        plen = rng.randint(8, 32)
        ntok = max(8, min(128, int(8 * rng.paretovariate(1.2))))
        arrivals.append((t, [rng.randrange(256)
                             for _ in range(plen)], ntok))

    def run(policy):
        eng = LLMEngine(
            ToyAdapter(step_delay_s=0.003, per_seq_delay_s=0.0002,
                       per_prefill_token_delay_s=0.00005),
            EngineConfig(max_running=8, max_waiting=100000,
                         max_prefill_tokens=256, num_blocks=4096,
                         block_size=16, max_seq_len=512,
                         policy=policy))
        results = []
        lock = threading.Lock()

        def consume(sched_abs, sid):
            cur, toks, first = 0, 0, None
            while True:
                ch = eng.poll(sid, cur, max_wait_s=30.0)
                if ch["tokens"] and first is None:
                    first = time.time()
                toks += len(ch["tokens"])
                cur = ch["cursor"]
                if ch["done"]:
                    break
            with lock:
                results.append(
                    (max(0.0, (first or time.time()) - sched_abs),
                     toks))

        threads = []
        t0 = time.time()
        for (ta, prompt, ntok) in arrivals:
            delay = t0 + ta - time.time()
            if delay > 0:
                time.sleep(delay)
            sid = eng.add_request(
                prompt, SamplingParams(max_new_tokens=ntok))
            th = threading.Thread(target=consume, args=(t0 + ta, sid))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        makespan = time.time() - t0
        eng.stop()
        ttfts = sorted(r[0] for r in results)
        tokens = sum(r[1] for r in results)

        def q(frac):
            return round(
                ttfts[min(len(ttfts) - 1, int(frac * len(ttfts)))]
                * 1e3, 2)

        return {"tokens": tokens,
                "makespan_s": round(makespan, 3),
                "tokens_per_s": round(tokens / makespan, 2),
                "ttft_p50_ms": q(0.50), "ttft_p99_ms": q(0.99)}

    cont = run("continuous")
    static = run("static")

    # ---- radix prefix cache: Zipf-skewed tenants share system prompts
    # Prefill cost dominates here (1 ms/prompt token): skipping the
    # cached shared-prefix pages is a direct throughput win.  The SAME
    # arrival schedule runs warm (radix cache on) and cold (off);
    # greedy decoding, so the token streams must be identical.
    p_duration = float(os.environ.get("LLM_BENCH_PREFIX_DURATION_S",
                                      1.5 if smoke else 6.0))
    p_rate = 16.0 if smoke else 30.0
    n_tenants = 6
    prng = random.Random(77)
    zipf_w = [1.0 / (i + 1) ** 1.4 for i in range(n_tenants)]
    prefixes = [[random.Random(f"sys:{i}").randrange(256)
                 for _ in range(48)] for i in range(n_tenants)]
    p_arrivals = []
    t = 0.0
    while True:
        t += prng.expovariate(p_rate)
        if t >= p_duration:
            break
        tenant = prng.choices(range(n_tenants), weights=zipf_w)[0]
        suffix = [prng.randrange(256)
                  for _ in range(prng.randint(6, 14))]
        p_arrivals.append((t, prefixes[tenant] + suffix,
                           prng.randint(6, 12)))
    p_prompt_tokens = sum(len(a[1]) for a in p_arrivals)

    def run_prefix(enable):
        eng = LLMEngine(
            ToyAdapter(step_delay_s=0.001, per_seq_delay_s=0.0001,
                       per_prefill_token_delay_s=0.001),
            EngineConfig(max_running=8, max_waiting=100000,
                         max_prefill_tokens=512, num_blocks=4096,
                         block_size=16, max_seq_len=512,
                         enable_prefix_cache=enable))
        outs = [None] * len(p_arrivals)
        ttfts = [0.0] * len(p_arrivals)

        def consume(i, sched_abs, sid):
            cur, toks, first = 0, [], None
            while True:
                ch = eng.poll(sid, cur, max_wait_s=30.0)
                if ch["tokens"] and first is None:
                    first = time.time()
                toks.extend(ch["tokens"])
                cur = ch["cursor"]
                if ch["done"]:
                    break
            outs[i] = toks
            ttfts[i] = max(0.0, (first or time.time()) - sched_abs)

        threads = []
        t0 = time.time()
        for i, (ta, prompt, ntok) in enumerate(p_arrivals):
            delay = t0 + ta - time.time()
            if delay > 0:
                time.sleep(delay)
            sid = eng.add_request(
                prompt, SamplingParams(max_new_tokens=ntok))
            th = threading.Thread(target=consume,
                                  args=(i, t0 + ta, sid))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        makespan = time.time() - t0
        hit_tokens = int(eng.metrics().get("cache_hit_tokens_total", 0))
        eng.stop()
        q = sorted(ttfts)
        tokens = sum(len(o) for o in outs)
        return {"tokens_per_s": round(tokens / makespan, 2),
                "ttft_p99_ms": round(
                    q[min(len(q) - 1, int(0.99 * len(q)))] * 1e3, 2),
                "hit_tokens": hit_tokens, "outs": outs}

    warm = run_prefix(True)
    cold = run_prefix(False)
    prefix_ratio = round(warm["tokens_per_s"]
                         / max(cold["tokens_per_s"], 1e-9), 2)
    prefix_hit_ratio = round(warm["hit_tokens"]
                             / max(p_prompt_tokens, 1), 3)

    # ---- prefill/decode disaggregation vs unified, same 2-engine budget
    # Disagg: one prefill-role engine hands the prompt KV (inline blob —
    # the serve path ships the identical blob over plasmax ring slots)
    # to one decode-role engine, which never runs a prefill.  Unified:
    # two engines round-robin, each interleaving prefills into its
    # decode batch.  The prefill bubbles (~50 ms at these costs) land
    # in the unified engines' inter-token gaps — TPOT p99 is the gate.
    d_duration = 1.5 if smoke else 6.0
    d_rate = 5.0 if smoke else 8.0
    drng = random.Random(99)
    d_arrivals = []
    t = 0.0
    while True:
        t += drng.expovariate(d_rate)
        if t >= d_duration:
            break
        plen = 32 + drng.randint(8, 16)
        d_arrivals.append((t, [drng.randrange(256) for _ in range(plen)],
                           drng.randint(8, 16)))

    def _mk_eng():
        return LLMEngine(
            ToyAdapter(step_delay_s=0.001, per_seq_delay_s=0.0001,
                       per_prefill_token_delay_s=0.001),
            EngineConfig(max_running=8, max_waiting=100000,
                         max_prefill_tokens=512, num_blocks=4096,
                         block_size=16, max_seq_len=512))

    def _drain_timed(eng, sid, first=None):
        """Poll a stream to completion; returns (t_first, t_last, n)."""
        cur, n, last = 0, 0, None
        while True:
            ch = eng.poll(sid, cur, max_wait_s=30.0)
            if ch["tokens"]:
                if first is None:
                    first = time.time()
                last = time.time()
                n += len(ch["tokens"])
            cur = ch["cursor"]
            if ch["done"]:
                break
        return first, last or first or time.time(), n

    def run_disagg():
        pre, dec = _mk_eng(), _mk_eng()
        rows = []
        lock = threading.Lock()

        def one(sched_abs, prompt, ntok):
            sp = SamplingParams(max_new_tokens=ntok)
            sid = pre.prefill_export(prompt, sp)
            t_first, _, _ = _drain_timed(pre, sid)
            export = pre.take_export(sid) or {}
            first_tok = export.get("first_token")
            if first_tok is None:
                return
            sid2 = dec.adopt_request(prompt, int(first_tok),
                                     export.get("kv"), sp)
            _, t_last, n = _drain_timed(dec, sid2, first=t_first)
            with lock:
                rows.append((max(0.0, t_first - sched_abs),
                             (t_last - t_first) / max(n - 1, 1), n))

        threads = []
        t0 = time.time()
        for (ta, prompt, ntok) in d_arrivals:
            delay = t0 + ta - time.time()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=one,
                                  args=(t0 + ta, prompt, ntok))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        pre.stop()
        dec.stop()
        return rows

    def run_unified_pair():
        engs = [_mk_eng(), _mk_eng()]
        rows = []
        lock = threading.Lock()

        def one(eng, sched_abs, prompt, ntok):
            sid = eng.add_request(
                prompt, SamplingParams(max_new_tokens=ntok))
            t_first, t_last, n = _drain_timed(eng, sid)
            with lock:
                rows.append((max(0.0, t_first - sched_abs),
                             (t_last - t_first) / max(n - 1, 1), n))

        threads = []
        t0 = time.time()
        for i, (ta, prompt, ntok) in enumerate(d_arrivals):
            delay = t0 + ta - time.time()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(
                target=one, args=(engs[i % 2], t0 + ta, prompt, ntok))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        for e in engs:
            e.stop()
        return rows

    def _q99(rows, idx):
        vals = sorted(r[idx] for r in rows)
        if not vals:
            return 0.0
        return round(vals[min(len(vals) - 1,
                              int(0.99 * len(vals)))] * 1e3, 2)

    disagg_rows = run_disagg()
    unified_rows = run_unified_pair()

    # paged-attention kernel numerics vs the whole-kv reference
    # (tier-1 re-asserts this; the bench records the number)
    import numpy as np

    import jax.numpy as jnp
    from ray_tpu.ops import attention as A
    r2 = np.random.RandomState(0)
    B, H, Hkv, D, bs, NB = 3, 8, 2, 16, 8, 4
    lengths = jnp.asarray([5, 17, 30], jnp.int32)
    k_pages = jnp.asarray(r2.randn(1 + B * NB, bs, Hkv, D), jnp.float32)
    v_pages = jnp.asarray(r2.randn(1 + B * NB, bs, Hkv, D), jnp.float32)
    bt = jnp.asarray(np.arange(1, 1 + B * NB).reshape(B, NB), jnp.int32)
    qq = jnp.asarray(r2.randn(B, H, D), jnp.float32)
    ref = A.paged_attention_reference(qq, k_pages, v_pages, bt, lengths)
    ker = A.paged_attention_decode(qq, k_pages, v_pages, bt, lengths,
                                   interpret=True)
    max_err = float(jnp.max(jnp.abs(ref - ker)))

    ratio = round(cont["tokens_per_s"]
                  / max(static["tokens_per_s"], 1e-9), 2)
    out = {
        "metric": "llm_serving",
        "requests": len(arrivals),
        "load_window_s": duration,
        "offered_rps": rate,
        "continuous_tokens_per_s": cont["tokens_per_s"],
        "static_tokens_per_s": static["tokens_per_s"],
        "tokens_per_s_ratio": ratio,
        "continuous_ttft_p50_ms": cont["ttft_p50_ms"],
        "continuous_ttft_p99_ms": cont["ttft_p99_ms"],
        "static_ttft_p50_ms": static["ttft_p50_ms"],
        "static_ttft_p99_ms": static["ttft_p99_ms"],
        "continuous_makespan_s": cont["makespan_s"],
        "static_makespan_s": static["makespan_s"],
        "paged_kernel_max_err": max_err,
        "gate_throughput_ok": ratio >= 1.5,
        "gate_ttft_ok":
            cont["ttft_p99_ms"] <= static["ttft_p99_ms"],
        "gate_numerics_ok": max_err < 1e-4,
        # radix prefix cache (warm vs cold, same Zipf tenant schedule)
        "prefix_requests": len(p_arrivals),
        "prefix_warm_tokens_per_s": warm["tokens_per_s"],
        "prefix_cold_tokens_per_s": cold["tokens_per_s"],
        "prefix_tokens_per_s_ratio": prefix_ratio,
        "prefix_warm_ttft_p99_ms": warm["ttft_p99_ms"],
        "prefix_cold_ttft_p99_ms": cold["ttft_p99_ms"],
        "prefix_hit_ratio": prefix_hit_ratio,
        "gate_prefix_throughput_ok": prefix_ratio >= 1.3,
        "gate_prefix_ttft_ok":
            warm["ttft_p99_ms"] <= cold["ttft_p99_ms"],
        "gate_prefix_identical_ok": warm["outs"] == cold["outs"],
        # prefill/decode disaggregation vs unified (2 engines each)
        "disagg_requests": len(d_arrivals),
        "disagg_ttft_p99_ms": _q99(disagg_rows, 0),
        "unified_ttft_p99_ms": _q99(unified_rows, 0),
        "disagg_tpot_p99_ms": _q99(disagg_rows, 1),
        "unified_tpot_p99_ms": _q99(unified_rows, 1),
        "gate_disagg_tpot_ok":
            _q99(disagg_rows, 1) <= _q99(unified_rows, 1),
    }
    print(json.dumps(out), flush=True)


# ----------------------------------------------------------------- supervise

def _attempt(force_cpu: bool):
    """One full attempt: raw control subprocess, then framework run."""
    _reap_framework_orphans()  # a crashed prior attempt must not linger
    raw, err = _run_raw_control(force_cpu)
    if raw is None:
        return None, err
    env = dict(os.environ, _BENCH_FRAMEWORK="1")
    env.pop("LIBTPU_INIT_ARGS", None)
    if force_cpu:
        env["_BENCH_FORCE_CPU"] = "1"
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE, text=True, env=env,
                            start_new_session=True)
    fw = None
    try:
        out, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
        for line in reversed(out.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    fw = json.loads(line)
                    break
                except ValueError:
                    continue
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        _reap_framework_orphans()
        return None, "framework run timed out"
    if fw is None or "img_per_sec_per_chip" not in fw:
        return None, f"framework run produced no result (rc={proc.returncode})"
    fw["raw_img_per_sec_per_chip"] = raw.get("img_per_sec_per_chip")
    if raw.get("img_per_sec_per_chip"):
        fw["framework_vs_raw"] = round(
            fw["img_per_sec_per_chip"] / raw["img_per_sec_per_chip"], 4)
    return fw, None


def _supervise():
    errors = []
    delay = 5.0
    ingest = _run_ingest_bench()  # CPU-only, runs before any TPU attempt
    for _ in range(ATTEMPTS):
        result, err = _attempt(force_cpu=False)
        if result is not None:
            result.update(ingest)
            value = result.pop("img_per_sec_per_chip")
            _emit(value, round(value / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
                  **result)
            return
        errors.append(err)
        time.sleep(delay)
        delay = min(delay * 2, 30.0)
    result, err = _attempt(force_cpu=True)
    if result is not None:
        result.update(ingest)
        value = result.pop("img_per_sec_per_chip")
        result["fallback"] = "cpu"
        result["tpu_errors"] = errors[:3]
        _emit(value, round(value / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
              **result)
        return
    errors.append(err)
    _emit(0.0, 0.0, error="; ".join(str(e) for e in errors)[:500])


def _trace_bench_main():
    """Tracing bench (_BENCH_TRACE=1): (a) span-pipeline throughput —
    record_span + flush rates on a discard sender; (b) the overhead
    gate — serve closed-loop RPS through the handle path with default
    sampling vs RTPU_TRACING=0, one fresh cluster per mode so replicas
    inherit the env. Gate (PERF.md): on/off RPS ratio >= 0.95."""
    _force_cpu_platform()
    import threading

    import numpy as np

    from ray_tpu._private import tracing

    duration = float(os.environ.get("BENCH_TRACE_DURATION", 3.0))
    clients = int(os.environ.get("BENCH_TRACE_CLIENTS", 8))
    service_ms = float(os.environ.get("BENCH_TRACE_SERVICE_MS", 2.0))
    out = {"duration_s": duration, "clients": clients,
           "service_ms": service_ms}

    # ---- (a) span pipeline microbench: pure record + flush cost ----
    # forced sample=1.0 so this measures the RECORDED path, not the
    # early head-sample drop (the serve section below measures the
    # default-sampling mix)
    prev_sample = os.environ.get("RTPU_TRACE_SAMPLE")
    os.environ["RTPU_TRACE_SAMPLE"] = "1.0"
    tracing.refresh()
    tracing.set_sender(lambda p: True)  # count-and-discard
    try:
        n = 200_000
        now = time.time()
        t0 = time.perf_counter()
        for i in range(n):
            tracing.record_span("bench-trace", f"s{i}", "bench",
                                phase="execute", start_ts=now,
                                end_ts=now + 0.001)
        record_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        while tracing.pending_count():
            tracing.flush()
        flush_dt = time.perf_counter() - t0
        out["record_spans_per_s"] = round(n / record_dt)
        out["record_us_per_span"] = round(record_dt / n * 1e6, 3)
        out["flush_spans_per_s"] = round(n / max(flush_dt, 1e-9))
    finally:
        tracing.set_sender(None)
        tracing.stop_flusher()
        if prev_sample is None:
            os.environ.pop("RTPU_TRACE_SAMPLE", None)
        else:
            os.environ["RTPU_TRACE_SAMPLE"] = prev_sample
        tracing.refresh()

    # ---- (b) serve closed-loop: tracing on vs off ----
    def closed_loop(fn, n_clients, dur):
        lat, errors = [], [0]
        lock = threading.Lock()
        stop = time.perf_counter() + dur

        def worker():
            local = []
            while time.perf_counter() < stop:
                t0 = time.perf_counter()
                try:
                    fn()
                except Exception:
                    with lock:
                        errors[0] += 1
                    continue
                local.append(time.perf_counter() - t0)
            with lock:
                lat.extend(local)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not lat:
            return {"rps": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                    "errors": errors[0]}
        arr = np.asarray(lat)
        return {"rps": round(len(lat) / dur, 1),
                "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 2),
                "errors": errors[0]}

    class TraceEcho:
        def __init__(self, service_s):
            self.service_s = service_s

        def __call__(self, x):
            time.sleep(self.service_s)
            return x

    # Interleaved A/B windows in ONE cluster: per-run RPS drifts ~8% on
    # the 1-core box, far above the 5% gate, so mode-per-cluster
    # comparisons measure thermal luck. Tracing is driver-gated
    # (unsampled/disabled requests carry no trace ctx, so the replica
    # does zero tracing work), which makes toggling RTPU_TRACING in the
    # driver between back-to-back windows a fair whole-path comparison.
    import ray_tpu
    from ray_tpu import serve
    prev = os.environ.get("RTPU_TRACING")
    os.environ.pop("RTPU_TRACING", None)  # replicas: library default
    tracing.refresh()
    rounds = int(os.environ.get("BENCH_TRACE_ROUNDS", 3))
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024,
                 _system_config={"prestart_workers": False})
    try:
        from ray_tpu.serve.handle import _reset_router
        _reset_router()
        h = serve.run(
            serve.deployment(num_replicas=2,
                             max_concurrent_queries=32)(
                TraceEcho).bind(service_ms / 1e3),
            name="trace_bench", http_port=None)
        seq = iter(range(1 << 30))

        def call():
            import ray_tpu as rt
            rt.get(h.remote(
                1, __rtpu_request_id__=f"tb-{next(seq)}"),
                timeout=30.0)

        for _ in range(16):
            call()  # warm replicas + router + span path
        stats = {"off": [], "on": []}
        for _ in range(rounds):
            for mode, env in (("off", "0"), ("on", "1")):
                os.environ["RTPU_TRACING"] = env
                tracing.refresh()
                stats[mode].append(closed_loop(call, clients, duration))
        for mode in ("off", "on"):
            best = max(s["rps"] for s in stats[mode])
            out[f"serve_{mode}_rps"] = round(
                sum(s["rps"] for s in stats[mode]) / rounds, 1)
            out[f"serve_{mode}_rps_best"] = best
            out[f"serve_{mode}_p50_ms"] = round(
                sum(s["p50_ms"] for s in stats[mode]) / rounds, 2)
            out[f"serve_{mode}_p99_ms"] = round(
                max(s["p99_ms"] for s in stats[mode]), 2)
            out[f"serve_{mode}_errors"] = sum(
                s["errors"] for s in stats[mode])
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()
        if prev is None:
            os.environ.pop("RTPU_TRACING", None)
        else:
            os.environ["RTPU_TRACING"] = prev
        tracing.refresh()
    if out.get("serve_off_rps"):
        out["trace_overhead_rps_ratio"] = round(
            out["serve_on_rps"] / out["serve_off_rps"], 3)
        out["trace_overhead_ok"] = \
            out["trace_overhead_rps_ratio"] >= 0.95
    print(json.dumps({"metric": "tracing", **out}), flush=True)


def main():
    if os.environ.get("_BENCH_RAW"):
        try:
            _raw_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_DATA_INGEST"):
        try:
            _data_ingest_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_CKPT"):
        try:
            _ckpt_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_SERVE"):
        try:
            _serve_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_SERVE_HA"):
        try:
            _serve_ha_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_CHAOS"):
        try:
            _chaos_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_STATE"):
        try:
            _state_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_DAG"):
        try:
            _dag_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_NET"):
        try:
            _net_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_TRACE"):
        try:
            _trace_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_GAMEDAY"):
        try:
            _gameday_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_LLM"):
        try:
            _llm_bench_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses output
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    elif os.environ.get("_BENCH_FRAMEWORK"):
        try:
            metrics = _framework_main()
            print(json.dumps(metrics), flush=True)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
    else:
        _supervise()


if __name__ == "__main__":
    main()
