"""Flagship benchmark: ResNet-50 synthetic-data training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: ResNet-50 images/sec/chip, bf16, synthetic ImageNet shapes —
the reference's headline Train benchmark (reference:
release/air_tests/air_benchmarks/mlperf-train/resnet50_ray_air.py:194-196,
torchvision resnet50 under TorchTrainer/DDP). Baseline: 2500 images/s per
A100 (MLPerf-class DDP throughput on the reference's GPU templates); the
north star (BASELINE.json) is matching A100 throughput per chip.

Runs on whatever jax backend is present: the real TPU chip under the
driver, or CPU (tiny shapes) for smoke runs.
"""

from __future__ import annotations

import json
import os
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0  # A100 MLPerf-class ResNet-50 DDP


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.resnet import create_resnet
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import make_image_classifier_trainer, put_batch

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    n_dev = jax.local_device_count()

    if on_tpu:
        batch = int(os.environ.get("BENCH_BATCH", 256)) * n_dev
        image_size = 224
        steps, warmup = 20, 3
        dtype = jnp.bfloat16
    else:  # CPU smoke: tiny shapes, same code path
        batch = 8 * n_dev
        image_size = 32
        steps, warmup = 3, 1
        dtype = jnp.float32

    spec = MeshSpec(dp=n_dev)
    mesh = spec.build(jax.devices()[:n_dev])
    model = create_resnet("resnet50", num_classes=1000, dtype=dtype)
    trainer = make_image_classifier_trainer(
        model, mesh=mesh, spec=spec,
        input_shape=(1, image_size, image_size, 3))

    state = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    images = rng.standard_normal(
        (batch, image_size, image_size, 3), dtype=np.float32)
    labels = rng.integers(0, 1000, (batch,), dtype=np.int32)
    dev_batch = put_batch(trainer, {"image": images, "label": labels})

    # NB: sync via device_get of the final loss, not block_until_ready —
    # the serial state dependency forces every queued step to finish, and
    # device_get is a proven barrier on the tunneled TPU platform here.
    for _ in range(warmup):
        state, metrics = trainer.step(state, dev_batch)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.step(state, dev_batch)
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0

    img_per_sec = batch * steps / dt
    img_per_sec_per_chip = img_per_sec / n_dev

    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/s/chip",
        "vs_baseline": round(
            img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
