"""Flagship benchmark: ResNet-50 synthetic-data training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Metric: ResNet-50 images/sec/chip, bf16, synthetic ImageNet shapes —
the reference's headline Train benchmark (reference:
release/air_tests/air_benchmarks/mlperf-train/resnet50_ray_air.py:194-196,
torchvision resnet50 under TorchTrainer/DDP). Baseline: 2500 images/s per
A100 (MLPerf-class DDP throughput on the reference's GPU templates); the
north star (BASELINE.json) is matching A100 throughput per chip.

Hardening (round-1 BENCH failed with a transient backend `Unavailable`;
backend init can also HANG outright when the TPU tunnel stalls):
  - the benchmark body runs in a supervised child process; the supervisor
    requires a backend-ready marker within a timeout, kills a hung child,
    and retries with backoff — an in-process retry loop cannot recover
    from a hung PJRT client init;
  - if the TPU never comes up, a forced-CPU child still produces an
    honest (clearly labeled) number;
  - any unrecoverable failure still emits the ONE JSON line (value 0,
    "error" field) instead of a traceback, so the driver always parses.

Extras reported alongside the headline number: avg step time, compile
time, per-step FLOPs (from the compiled program's XLA cost analysis), and
MFU against the chip's peak bf16 FLOPs.
"""

from __future__ import annotations

import json
import os
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0  # A100 MLPerf-class ResNet-50 DDP

METRIC = "resnet50_images_per_sec_per_chip"
UNIT = "images/s/chip"

# Peak dense bf16 FLOP/s per chip, keyed by substring of device_kind.
_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


READY_MARKER = "#BENCH_BACKEND_READY"
INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT", 300))
RUN_TIMEOUT_S = float(os.environ.get("BENCH_RUN_TIMEOUT", 2400))
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", 3))


def _emit(value, vs_baseline, **extras):
    line = {"metric": METRIC, "value": value, "unit": UNIT,
            "vs_baseline": vs_baseline}
    line.update(extras)
    print(json.dumps(line))


def _compile_step(step_fn, state, batch):
    """AOT-compile the train step once; return (callable, flops, seconds).

    The compiled executable is used both for the timing loop and for the
    XLA cost analysis, so the (single-core-CPU-smoke-hostile) compile
    happens exactly once.
    """
    t0 = time.perf_counter()
    try:
        compiled = step_fn.lower(state, batch).compile()
    except Exception:
        return step_fn, None, time.perf_counter() - t0
    compile_s = time.perf_counter() - t0
    flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = float(ca.get("flops", 0.0))
        flops = f if f > 0 else None
    except Exception:
        pass
    return compiled, flops, compile_s


def _child_main():
    """Runs in the supervised child: init backend, signal readiness, run."""
    import sys

    if os.environ.get("_BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    devices = jax.devices()
    print(f"{READY_MARKER} platform={devices[0].platform}", flush=True)
    _run(devices)


def _supervise():
    """Spawn the benchmark child; kill + retry if backend init hangs or
    fails; fall back to a labeled CPU run; always emit one JSON line."""
    import subprocess
    import sys
    import threading

    def attempt(force_cpu: bool):
        env = dict(os.environ, _BENCH_CHILD="1")
        if force_cpu:
            env["_BENCH_FORCE_CPU"] = "1"
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, env=env, text=True)
        lines: list = []
        got_ready = threading.Event()
        done = threading.Event()

        def reader():
            for line in proc.stdout:
                line = line.strip()
                if line.startswith(READY_MARKER):
                    got_ready.set()
                elif line:
                    lines.append(line)
            done.set()

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        if not got_ready.wait(INIT_TIMEOUT_S):
            proc.kill()
            return None, "backend init timed out"
        if not done.wait(RUN_TIMEOUT_S):
            proc.kill()
            return None, "benchmark run timed out"
        proc.wait()
        for line in reversed(lines):
            try:
                return json.loads(line), None
            except ValueError:
                continue
        return None, f"child exited rc={proc.returncode} with no JSON"

    errors = []
    delay = 5.0
    for i in range(ATTEMPTS):
        result, err = attempt(force_cpu=False)
        if result is not None and not result.get("error"):
            print(json.dumps(result))
            return
        errors.append(err or result.get("error"))
        time.sleep(delay)
        delay = min(delay * 2, 30.0)

    # TPU never came up: labeled CPU fallback so the driver still gets a
    # real measured number from the same code path.
    result, err = attempt(force_cpu=True)
    if result is not None:
        result["fallback"] = "cpu"
        result["tpu_errors"] = errors[:3]
        print(json.dumps(result))
        return
    errors.append(err)
    _emit(0.0, 0.0, error="; ".join(str(e) for e in errors)[:500])


def main():
    if os.environ.get("_BENCH_CHILD"):
        try:
            _child_main()
        except Exception as e:  # noqa: BLE001 — supervisor parses this line
            _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}"[:500])
    else:
        _supervise()


def _run(devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.resnet import create_resnet
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import make_image_classifier_trainer, put_batch

    platform = devices[0].platform
    on_tpu = platform == "tpu"
    n_dev = jax.local_device_count()

    if on_tpu:
        batch = int(os.environ.get("BENCH_BATCH", 256)) * n_dev
        image_size = 224
        steps, warmup = 20, 3
        dtype = jnp.bfloat16
    else:  # CPU smoke: tiny shapes, same code path
        batch = 8 * n_dev
        image_size = 32
        steps, warmup = 3, 1
        dtype = jnp.float32

    spec = MeshSpec(dp=n_dev)
    mesh = spec.build(jax.devices()[:n_dev])
    model = create_resnet("resnet50", num_classes=1000, dtype=dtype)
    trainer = make_image_classifier_trainer(
        model, mesh=mesh, spec=spec,
        input_shape=(1, image_size, image_size, 3))

    state = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    images = rng.standard_normal(
        (batch, image_size, image_size, 3), dtype=np.float32)
    labels = rng.integers(0, 1000, (batch,), dtype=np.int32)
    dev_batch = put_batch(trainer, {"image": images, "label": labels})

    step, flops_per_step, compile_s = _compile_step(
        trainer.step, state, dev_batch)

    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    # NB: sync via device_get of the final loss, not block_until_ready —
    # the serial state dependency forces every queued step to finish, and
    # device_get is a proven barrier on the tunneled TPU platform here.
    for _ in range(warmup):
        state, metrics = step(state, dev_batch)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, dev_batch)
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0

    if profile_dir:
        jax.profiler.stop_trace()

    step_time = dt / steps
    img_per_sec = batch * steps / dt
    img_per_sec_per_chip = img_per_sec / n_dev

    extras = {
        "platform": platform,
        "n_chips": n_dev,
        "batch_per_chip": batch // n_dev,
        "step_time_ms": round(step_time * 1e3, 2),
        "compile_s": round(compile_s, 2),
    }
    if flops_per_step:
        extras["flops_per_step"] = flops_per_step
        peak = _peak_flops(devices[0].device_kind)
        if peak:
            extras["mfu"] = round(
                flops_per_step / step_time / (peak * n_dev), 4)
            extras["peak_bf16_flops_per_chip"] = peak

    _emit(round(img_per_sec_per_chip, 2),
          round(img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
          **extras)


if __name__ == "__main__":
    main()
