"""Profile the two bench steps (ResNet-50, GPT-2) on the real chip with
jax.profiler and print a per-op time breakdown — the xplane-driven
tuning loop the round-4 verdict asked for (VERDICT r4 "Next round" #1).

Usage: python bench_profile.py [resnet|gpt2|both] [--trace-dir DIR]
Run it directly on the TPU (not under tests' CPU pin).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import time


def _profile_model(which: str, trace_dir: str):
    import jax
    import numpy as np

    from bench import bench_loop, gpt2_loop  # reuse exact bench setup

    import jax.numpy as jnp
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import (make_causal_lm_trainer,
                                    make_image_classifier_trainer, put_batch)

    devices = jax.devices()
    n_dev = jax.local_device_count()
    spec = MeshSpec(dp=n_dev)
    mesh = spec.build(devices[:n_dev])

    if which == "resnet":
        from ray_tpu.models.resnet import create_resnet
        batch = 256 * n_dev
        model = create_resnet("resnet50", num_classes=1000,
                              dtype=jnp.bfloat16)
        trainer = make_image_classifier_trainer(
            model, mesh=mesh, spec=spec, input_shape=(1, 224, 224, 3))
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        images = rng.standard_normal((batch, 224, 224, 3), dtype=np.float32)
        labels = rng.integers(0, 1000, (batch,), dtype=np.int32)
        resident = put_batch(trainer, {"image": images, "label": labels})
    else:
        from ray_tpu.models.gpt2 import GPT2Config
        cfg = GPT2Config(vocab_size=50257, n_positions=1024, n_embd=768,
                         n_layer=12, n_head=12,
                         attention_backend="flash", dtype=jnp.bfloat16)
        batch = 16 * n_dev
        trainer = make_causal_lm_trainer(cfg, mesh=mesh, spec=spec)
        state = trainer.init(jax.random.PRNGKey(0))
        tokens = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch, 1024), dtype=np.int32)
        resident = put_batch(trainer, {"input_ids": tokens,
                                       "labels": tokens})

    step = trainer.step.lower(state, resident).compile()
    for _ in range(3):
        state, metrics = step(state, resident)
    float(jax.device_get(metrics["loss"]))

    run_dir = os.path.join(trace_dir, which)
    with jax.profiler.trace(run_dir):
        for _ in range(5):
            state, metrics = step(state, resident)
        float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(10):
        state, metrics = step(state, resident)
    float(jax.device_get(metrics["loss"]))
    dt = (time.perf_counter() - t0) / 10
    return run_dir, dt


def summarize(run_dir: str, top: int = 30):
    """Aggregate device-lane op durations from the chrome trace."""
    from ray_tpu.util.tpu_profiler import load_chrome_events

    events = load_chrome_events(run_dir)
    # device lanes: pid/tid names carrying "TPU" / XLA op events have
    # 'dur' and names like fusion.N, copy.N, etc.
    by_name = collections.Counter()
    counts = collections.Counter()
    meta_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            meta_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", ""))
    device_tids = {k for k, v in meta_names.items()
                   if "XLA Op" in v or "Steps" in v or "TensorFlow Op" in v}
    for e in events:
        if e.get("ph") != "X":
            continue
        lane = meta_names.get((e.get("pid"), e.get("tid")), "")
        if not ("XLA Op" in lane or "TensorFlow Op" in lane):
            continue
        name = e.get("name", "?")
        by_name[name] += e.get("dur", 0)
        counts[name] += 1
    total = sum(by_name.values())
    rows = []
    for name, dur in by_name.most_common(top):
        rows.append({"op": name[:90], "us": dur, "n": counts[name],
                     "pct": round(100 * dur / max(total, 1), 1)})
    return {"total_us": total, "lanes": sorted(
        {v for v in meta_names.values() if v}), "rows": rows}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    trace_dir = os.environ.get("BENCH_TRACE_DIR", "/tmp/bench_profile")
    models = ["resnet", "gpt2"] if which == "both" else [which]
    for m in models:
        run_dir, dt = _profile_model(m, trace_dir)
        print(f"\n=== {m}: step {dt * 1e3:.2f} ms ===")
        s = summarize(run_dir)
        print(f"lanes: {s['lanes'][:8]}")
        print(f"device total {s['total_us'] / 1e3:.1f} ms over trace")
        for r in s["rows"]:
            print(f"  {r['pct']:5.1f}%  {r['us'] / 1e3:9.2f} ms  n={r['n']:<4d} {r['op']}")


if __name__ == "__main__":
    main()
